"""kfslint device tier — XLA/JAX hot-path discipline rules.

The repo's whole perf story rests on two invariants nothing enforced
until now: decode waves never synchronize with the host implicitly,
and jitted programs are compiled per *bucket*, never per request.
Each rule here encodes a defect class that silently destroys MFU
instead of crashing:

- `host-sync`: an implicit device→host transfer (`float()`/`int()`/
  `bool()`/`.item()`/`.tolist()`/`np.asarray` on a value data-flowed
  from a `jax.*` call or a jitted dispatch) inside an `async def` or
  a wave/dispatch-named sync function joins the device stream on the
  spot — one stray `float(logits[0])` turns an async pipeline into a
  lock-step one.  The *sanctioned* fetch points (`_fetch_wave`, the
  engine's result fetch) carry line-tight pragmas naming themselves
  sanctioned; everything else must fetch on the executor.
- `jit-recompile-hazard`: a request-derived Python size (`len(...)`,
  `.size`, `.shape[i]`) reaching a jitted callable — directly or as
  an array-constructor dimension — without passing through a
  bucketing call compiles one executable per distinct request shape
  (the recompile storm `engine/buckets.py` exists to prevent).  Also
  flags f-strings and unhashable literals in `static_argnums`
  positions: every distinct value is its own cache entry (or a
  TypeError at trace time).
- `blocking-dispatch`: device work (a jitted callable,
  `block_until_ready`, `device_put`, or `jax.jit` itself) invoked in
  an `async def` body stalls the event loop for device/compile time —
  the device twin of `async-blocking`; the same calls under a held
  `threading` lock convoy every worker behind a dispatch (the
  `await-under-lock` class extended to device work).
- `prng-key-reuse`: the same `jax.random` key consumed by two sample
  calls without an intervening `split`/`fold_in` silently correlates
  the draws — two "independent" sampling noises become identical.

Dataflow is per-function and deliberately shallow (assignment-chain
taint, no cross-function propagation): deep inference would guess,
and a rule that guesses trains people to ignore it.  Two conventions
make the shallow analysis precise where it matters: device handles
passed between wave helpers are named `*_h` (taint sources), and
sync hot-path helpers carry a wave/dispatch/prefill/decode/fetch name
segment (scope markers).
"""

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from kfserving_tpu.tools.analyzers.asyncrules import (
    _classify_locks,
    _import_aliases,
    _lockish_name,
    _resolve,
)
from kfserving_tpu.tools.analyzers.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
    is_test_function,
)

# Sync functions with one of these whole snake_case segments in their
# name are hot-path device code (they run on the engine's enqueue/
# fetch executors): `_fetch_wave`, `_execute_sync`,
# `_enqueue_prefill_group`.  `decoder_tiny` ("decoder") is not.
_HOT_SEGMENTS = {"wave", "waves", "dispatch", "execute", "prefill",
                 "decode", "fetch"}

# Attribute access that yields host METADATA of a device array, not
# its contents — `int(x.shape[0])` is free and must not taint.
_METADATA_ATTRS = {"shape", "dtype", "ndim", "size", "sharding",
                   "device", "devices"}

_DEVICE_HANDLE_PARAM = re.compile(r"_h\d*$")


def _taint_target(tainted: Set[str], target: ast.AST) -> None:
    """Record an assignment target (Name, self-attribute, or any
    nesting of tuple/list/starred unpacking) into a taint set."""
    if isinstance(target, ast.Name):
        tainted.add(target.id)
    elif isinstance(target, ast.Attribute):
        tainted.add(target.attr)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _taint_target(tainted, elt)
    elif isinstance(target, ast.Starred):
        _taint_target(tainted, target.value)


def _untaint_target(tainted: Set[str], target: ast.AST) -> None:
    """Reassignment from a clean RHS KILLS taint — `toks = await
    loop.run_in_executor(ex, fetch, toks)` refetches through the
    executor into the same name, and the name is host-clean after."""
    if isinstance(target, ast.Name):
        tainted.discard(target.id)
    elif isinstance(target, ast.Attribute):
        tainted.discard(target.attr)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _untaint_target(tainted, elt)
    elif isinstance(target, ast.Starred):
        _untaint_target(tainted, target.value)


def _hot_sync_name(name: str) -> bool:
    return any(seg in _HOT_SEGMENTS for seg in name.lower().split("_"))


_is_test_function = is_test_function


def _call_parts(call: ast.Call) -> Tuple[Optional[str], List[str]]:
    name = dotted_name(call.func)
    return name, (name.split(".") if name else [])


def _is_device_call(call: ast.Call, aliases: Dict[str, str],
                    jitted: Set[str]) -> bool:
    """Does this call produce (or consume into) device values — a
    `jax.*`/`jnp.*` op, a jitted callable, or a device placement?"""
    name, parts = _call_parts(call)
    if name is None:
        return False
    resolved = _resolve(name, aliases)
    if resolved == "jax" or resolved.startswith("jax."):
        return True
    # `self._jnp.asarray(...)` / `self._jax.device_put(...)`: the
    # engine's stashed module handles.
    if any(p in ("jax", "jnp", "_jax", "_jnp") for p in parts[:-1]):
        return True
    bare = parts[-1]
    return bare in jitted or bare in ("device_put",
                                      "block_until_ready")


def collect_jitted(tree: ast.Module, aliases: Dict[str, str]
                   ) -> Dict[str, Tuple[int, ...]]:
    """{bare callable name: static_argnums positions} for every
    jit-wrapped callable the file creates — `f = jax.jit(g, ...)`
    assignments (Name or attribute targets: `self._decode = ...`) and
    `@jax.jit` / `@partial(jax.jit, ...)` decorated defs."""

    def _jit_call(node: ast.AST) -> Optional[ast.Call]:
        if not isinstance(node, ast.Call):
            return None
        name = dotted_name(node.func)
        if name is None:
            return None
        resolved = _resolve(name, aliases)
        if resolved in ("jax.jit", "jax.pjit", "pjit.pjit"):
            return node
        # `partial(jax.jit, static_argnums=...)` decorator spelling:
        # the partial call carries the static positions.
        if resolved.rsplit(".", 1)[-1] == "partial" and node.args:
            inner_name = dotted_name(node.args[0])
            if inner_name and _resolve(inner_name, aliases) in (
                    "jax.jit", "jax.pjit"):
                return node
        return None

    def _static_positions(call: ast.Call) -> Tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                v = kw.value
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = []
                    for elt in v.elts:
                        if isinstance(elt, ast.Constant) \
                                and isinstance(elt.value, int):
                            out.append(elt.value)
                    return tuple(out)
        return ()

    jitted: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            call = _jit_call(node.value)
            if call is None:
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    jitted[target.id] = _static_positions(call)
                elif isinstance(target, ast.Attribute):
                    jitted[target.attr] = _static_positions(call)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = _jit_call(dec)
                if call is not None:
                    jitted[node.name] = _static_positions(call)
                    continue
                name = dotted_name(dec)
                if name and _resolve(name, aliases) in ("jax.jit",
                                                        "jax.pjit"):
                    jitted[node.name] = ()
    return jitted


def _iter_scoped_functions(tree: ast.Module
                           ) -> Iterator[Tuple[ast.AST, str]]:
    """Every function the device rules scope to: all `async def`s plus
    sync defs with a hot-path name segment.  Each is scanned
    independently; the statement walkers below never descend into
    nested defs (they get their own visit if in scope)."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                or _is_test_function(node.name):
            continue
        if isinstance(node, ast.AsyncFunctionDef):
            yield node, f"async def {node.name}"
        elif _hot_sync_name(node.name):
            yield node, f"def {node.name}"


# -- rule 1: host-sync -------------------------------------------------------

_SCALAR_SINKS = {"float", "int", "bool"}
_METHOD_SINKS = {"item", "tolist"}
_FETCH_FNS = {"numpy.asarray", "numpy.array", "jax.device_get"}


class _TaintScan:
    """One function body's device-value taint walk (source order,
    branch bodies share the taint set — a value tainted on any path
    stays tainted; over-approximation is the right failure mode for a
    transfer rule backed by line-tight pragmas)."""

    def __init__(self, rule: "HostSyncRule", fn, where: str,
                 ctx: FileContext, aliases: Dict[str, str],
                 jitted: Set[str], findings: List[Finding]):
        self.rule = rule
        self.fn = fn
        self.where = where
        self.ctx = ctx
        self.aliases = aliases
        self.jitted = jitted
        self.findings = findings
        self.tainted: Set[str] = set()
        for arg in (list(fn.args.posonlyargs) + list(fn.args.args)
                    + list(fn.args.kwonlyargs)):
            # Device-handle naming convention: `toks_h`, `lp_h` — a
            # handle passed between wave helpers is still on device.
            if _DEVICE_HANDLE_PARAM.search(arg.arg):
                self.tainted.add(arg.arg)

    # -- expression taint --------------------------------------------------
    def expr_tainted(self, expr: Optional[ast.AST]) -> bool:
        if expr is None:
            return False
        for node in self._walk_expr(expr):
            if isinstance(node, ast.Name) and node.id in self.tainted:
                return True
            if isinstance(node, ast.Attribute) \
                    and node.attr in self.tainted \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in ("self", "cls"):
                return True
            if isinstance(node, ast.Call) \
                    and _is_device_call(node, self.aliases,
                                        self.jitted):
                return True
        return False

    @staticmethod
    def _walk_expr(expr: ast.AST) -> Iterator[ast.AST]:
        stack = [expr]
        while stack:
            node = stack.pop()
            # `.shape[0]` / `.dtype` etc. are host metadata — a sink
            # over them is free, so taint must not flow through.
            if isinstance(node, ast.Attribute) \
                    and node.attr in _METADATA_ATTRS:
                continue
            if isinstance(node, ast.Lambda):
                continue  # examined separately via _lambda_args
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- sinks -------------------------------------------------------------
    def _sink(self, call: ast.Call) -> Optional[str]:
        """If `call` is a host-materialization, name the sink."""
        if isinstance(call.func, ast.Name) \
                and call.func.id in _SCALAR_SINKS:
            if any(self.expr_tainted(a) for a in call.args):
                return f"{call.func.id}()"
            return None
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _METHOD_SINKS:
            # Checked before dotted-name resolution: a subscripted
            # receiver (`toks[0].item()`) has no dotted name.
            if self.expr_tainted(call.func.value):
                return f".{call.func.attr}()"
            return None
        name, parts = _call_parts(call)
        if name is None:
            return None
        resolved = _resolve(name, self.aliases)
        if (resolved in _FETCH_FNS
                or parts[-1] == "asarray"
                and any(p in ("np", "numpy") for p in parts[:-1])):
            if any(self.expr_tainted(a) for a in call.args):
                return f"{name}()"
        return None

    def _fire(self, node: ast.AST, sink: str) -> None:
        self.findings.append(self.ctx.finding(
            self.rule.id, node,
            f"implicit device->host sync: {sink} on a value from "
            f"jax/engine dispatch inside '{self.where}' joins the "
            f"device stream on the spot — fetch on the executor, or "
            f"pragma the line as a sanctioned fetch site"))

    def _scan_call(self, call: ast.Call) -> None:
        sink = self._sink(call)
        if sink is not None:
            self._fire(call, sink)
            return
        # `tree.map(lambda a: np.asarray(a), out)`: a lambda applied
        # over a tainted argument fetches every leaf — scan the
        # lambda body with its params tainted.
        lambdas = [a for a in call.args
                   if isinstance(a, ast.Lambda)]
        if lambdas and any(self.expr_tainted(a) for a in call.args
                           if not isinstance(a, ast.Lambda)):
            for lam in lambdas:
                inner = set(self.tainted)
                inner.update(a.arg for a in lam.args.args)
                saved, self.tainted = self.tainted, inner
                for sub in ast.walk(lam.body):
                    if isinstance(sub, ast.Call):
                        s = self._sink(sub)
                        if s is not None:
                            self._fire(sub, s)
                self.tainted = saved

    # -- statements --------------------------------------------------------
    def _taint_target(self, target: ast.AST) -> None:
        _taint_target(self.tainted, target)

    def scan(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate execution context, separate visit
            # An awaited value crossed back through the event loop
            # (the executor already fetched it): `await fut` results
            # are host values, so strip Await before taint analysis.
            if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                value = stmt.value
                awaited = isinstance(value, ast.Await)
                if awaited:
                    value = value.value
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                if not awaited and self.expr_tainted(value):
                    for t in targets:
                        self._taint_target(t)
                elif not isinstance(stmt, ast.AugAssign):
                    # Clean (or awaited — already fetched) RHS: the
                    # reassigned name is host-clean now.  AugAssign
                    # keeps old taint (x += clean stays device).
                    for t in targets:
                        _untaint_target(self.tainted, t)
            if isinstance(stmt, (ast.For, ast.AsyncFor)) \
                    and self.expr_tainted(stmt.iter):
                self._taint_target(stmt.target)
            # Comprehension targets over tainted iterables are
            # tainted too: `tuple(np.asarray(h) for h in lp_h)` is a
            # fetch per element.
            for node in ast.walk(stmt):
                if isinstance(node, (ast.GeneratorExp, ast.ListComp,
                                     ast.SetComp, ast.DictComp)):
                    for gen in node.generators:
                        if self.expr_tainted(gen.iter):
                            self._taint_target(gen.target)
            for call in self._stmt_calls(stmt):
                self._scan_call(call)
            for body in self._child_bodies(stmt):
                self.scan(body)

    @staticmethod
    def _stmt_calls(stmt: ast.stmt) -> Iterator[ast.Call]:
        """Call nodes belonging to THIS statement (not to child
        blocks or nested defs)."""
        bodies = set()
        for body in _TaintScan._child_bodies(stmt):
            for s in body:
                bodies.add(s)
        stack: List[ast.AST] = [stmt]
        while stack:
            node = stack.pop()
            if node in bodies or isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _child_bodies(stmt: ast.stmt) -> Iterator[List[ast.stmt]]:
        for field in ("body", "orelse", "finalbody"):
            block = getattr(stmt, field, None)
            if isinstance(block, list) and block \
                    and isinstance(block[0], ast.stmt):
                yield block
        for handler in getattr(stmt, "handlers", []):
            yield handler.body


class HostSyncRule(Rule):
    id = "host-sync"
    description = ("implicit device->host transfer (float/int/bool/"
                   ".item/.tolist/np.asarray on a jax value) in an "
                   "async def or wave/dispatch function")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        aliases = _import_aliases(tree)
        jitted = set(collect_jitted(tree, aliases))
        findings: List[Finding] = []
        for fn, where in _iter_scoped_functions(tree):
            scan = _TaintScan(self, fn, where, ctx, aliases, jitted,
                              findings)
            scan.scan(fn.body)
        return iter(findings)


# -- rule 2: jit-recompile-hazard -------------------------------------------

_ARRAY_CTORS = {"zeros", "ones", "full", "empty", "arange"}
_CLEANSE_SEGMENTS = {"fit", "bucket", "buckets"}


def _cleansing_call(call: ast.Call) -> bool:
    """A call through the bucketing vocabulary quantizes its input:
    `policy.fit(n)`, `self._bucket_for(n)`, `pow2_buckets(n)`."""
    name, parts = _call_parts(call)
    if name is None:
        return False
    segs = set()
    for part in parts:
        segs.update(part.lower().split("_"))
    return bool(segs & _CLEANSE_SEGMENTS)


class _SizeScan:
    """Raw request-derived sizes (len()/.size/.shape[i]) flowing to
    jitted callables, per function, source order."""

    def __init__(self, rule: "JitRecompileHazardRule", fn, where: str,
                 ctx: FileContext, aliases: Dict[str, str],
                 jitted: Dict[str, Tuple[int, ...]],
                 findings: List[Finding]):
        self.rule = rule
        self.where = where
        self.ctx = ctx
        self.aliases = aliases
        self.jitted = jitted
        self.findings = findings
        self.tainted: Set[str] = set()

    def _size_source(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            if _cleansing_call(expr):
                return False
            name, _parts = _call_parts(expr)
            if name is not None \
                    and _resolve(name, self.aliases) == "len":
                return True
            # int()/round() launder nothing: int(len(x)) is still a
            # request-derived size.
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in ("int", "round", "abs",
                                         "min", "max"):
                return any(self.expr_tainted(a) for a in expr.args)
            return False
        if isinstance(expr, ast.Attribute) and expr.attr == "size":
            return True
        if isinstance(expr, ast.Subscript) \
                and isinstance(expr.value, ast.Attribute) \
                and expr.value.attr == "shape":
            return True
        return False

    def expr_tainted(self, expr: Optional[ast.AST]) -> bool:
        if expr is None:
            return False
        stack = [expr]
        while stack:
            node = stack.pop()
            if self._size_source(node):
                return True
            if isinstance(node, ast.Name) and node.id in self.tainted:
                return True
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) \
                        and node.func.id in ("int", "round", "abs",
                                             "min", "max"):
                    stack.extend(node.args)
                elif self._ctor_with_tainted_shape(node):
                    return True
                # Other calls launder: their result's SHAPE is the
                # callee's contract, not the argument's value.
                continue
            if isinstance(node, (ast.List, ast.Tuple, ast.Set,
                                 ast.Dict)):
                # `[n]` has static shape len-1: the VALUE is dynamic
                # but the trace signature is not.  (A display used AS
                # a constructor's shape argument is handled by
                # _ctor_with_tainted_shape, which iterates the elts
                # itself.)
                continue
            stack.extend(ast.iter_child_nodes(node))
        return False

    def _ctor_with_tainted_shape(self, call: ast.Call) -> bool:
        """`np.zeros((b, n))` with a raw-size `n`: the array's SHAPE
        is request-derived — exactly what recompiles."""
        name, parts = _call_parts(call)
        if name is None or not call.args:
            return False
        if parts[-1] not in _ARRAY_CTORS:
            return False
        shape = call.args[0]
        if isinstance(shape, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e) for e in shape.elts)
        return self.expr_tainted(shape)

    def _check_jit_call(self, call: ast.Call) -> None:
        name, parts = _call_parts(call)
        if name is None:
            return
        bare = parts[-1]
        if bare not in self.jitted:
            return
        for arg in call.args:
            if self.expr_tainted(arg):
                self.findings.append(self.ctx.finding(
                    self.rule.id, arg,
                    f"request-derived size reaches jitted "
                    f"'{bare}' in '{self.where}' without passing "
                    f"through a bucket fit — every distinct value "
                    f"compiles a new executable (route it through "
                    f"engine/buckets.py)"))
                break
        for pos in self.jitted.get(bare, ()):
            if pos >= len(call.args):
                continue
            arg = call.args[pos]
            if isinstance(arg, ast.JoinedStr):
                self.findings.append(self.ctx.finding(
                    self.rule.id, arg,
                    f"f-string in static_argnums position {pos} of "
                    f"jitted '{bare}' — every distinct rendering is "
                    f"its own compile-cache entry (recompile storm)"))
            elif isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                self.findings.append(self.ctx.finding(
                    self.rule.id, arg,
                    f"unhashable {type(arg).__name__.lower()} literal "
                    f"in static_argnums position {pos} of jitted "
                    f"'{bare}' — static args must be hashable (use a "
                    f"tuple)"))

    def scan(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                value = stmt.value
                if isinstance(value, ast.Await):
                    value = value.value
                if self.expr_tainted(value):
                    targets = (stmt.targets
                               if isinstance(stmt, ast.Assign)
                               else [stmt.target])
                    for t in targets:
                        _taint_target(self.tainted, t)
            for call in _TaintScan._stmt_calls(stmt):
                self._check_jit_call(call)
            for body in _TaintScan._child_bodies(stmt):
                self.scan(body)


class JitRecompileHazardRule(Rule):
    id = "jit-recompile-hazard"
    description = ("request-derived size reaches a jitted callable "
                   "without bucketing, or a non-hashable/f-string "
                   "value sits in a static_argnums position")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        aliases = _import_aliases(tree)
        jitted = collect_jitted(tree, aliases)
        if not jitted:
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                kind = ("async def"
                        if isinstance(node, ast.AsyncFunctionDef)
                        else "def")
                scan = _SizeScan(self, node, f"{kind} {node.name}",
                                 ctx, aliases, jitted, findings)
                scan.scan(node.body)
        return iter(findings)


# -- rule 3: blocking-dispatch ----------------------------------------------

def _dispatch_call(call: ast.Call, aliases: Dict[str, str],
                   jitted: Set[str]) -> Optional[str]:
    """Name the device dispatch/sync this call performs, if any."""
    name, parts = _call_parts(call)
    if name is None:
        return None
    bare = parts[-1]
    if bare in jitted:
        return f"jitted '{bare}'"
    if bare == "block_until_ready":
        return "block_until_ready()"
    resolved = _resolve(name, aliases)
    if resolved in ("jax.jit", "jax.pjit"):
        return "jax.jit() (trace+compile)"
    if resolved == "jax.device_put" or (
            bare == "device_put"
            and any(p in ("jax", "_jax") for p in parts[:-1])):
        return "device_put()"
    return None


class BlockingDispatchRule(Rule):
    id = "blocking-dispatch"
    description = ("device dispatch (jitted call, block_until_ready, "
                   "device_put, jax.jit) on the event loop or under "
                   "a held threading lock")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        aliases = _import_aliases(tree)
        jitted = set(collect_jitted(tree, aliases))
        lock_kinds = _classify_locks(tree, aliases)

        def is_threadlock(with_item: ast.withitem) -> Optional[str]:
            expr = with_item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func
            base = None
            if isinstance(expr, ast.Attribute):
                base = expr.attr
            elif isinstance(expr, ast.Name):
                base = expr.id
            if base is None:
                return None
            kinds = lock_kinds.get(base, set())
            if kinds == {"threading"} or (not kinds
                                          and _lockish_name(base)):
                return base
            return None

        funcs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        # Map each With statement to its enclosing function so the
        # test*-scoping policy applies to the lock branch too.
        with_owner: Dict[int, ast.AST] = {}
        for fn in funcs:
            for sub in _iter_own_nodes(fn.body):
                if isinstance(sub, ast.With):
                    with_owner[id(sub)] = fn

        # Lock pass first (emitted second): a dispatch under a held
        # lock gets the lock diagnosis, and the async pass skips it
        # rather than double-reporting the same call.
        lock_findings: List[Finding] = []
        covered: Set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.With):
                continue
            fn = with_owner.get(id(node))
            if fn is not None and _is_test_function(fn.name):
                continue
            for item in node.items:
                lock = is_threadlock(item)
                if lock is None:
                    continue
                for sub in _iter_own_nodes(node.body):
                    if isinstance(sub, ast.Call):
                        what = _dispatch_call(sub, aliases, jitted)
                        if what is not None:
                            covered.add(id(sub))
                            lock_findings.append(ctx.finding(
                                self.id, sub,
                                f"{what} under held lock `{lock}` — "
                                f"a dispatch (worse: a compile) "
                                f"convoys every thread waiting on "
                                f"the lock; dispatch outside the "
                                f"hold"))
                break
        for node in funcs:
            if not isinstance(node, ast.AsyncFunctionDef) \
                    or _is_test_function(node.name):
                continue
            for sub in _iter_own_nodes(node.body):
                if isinstance(sub, ast.Call) \
                        and id(sub) not in covered:
                    what = _dispatch_call(sub, aliases, jitted)
                    if what is not None:
                        yield ctx.finding(
                            self.id, sub,
                            f"{what} inside 'async def "
                            f"{node.name}' stalls the event loop "
                            f"for device/compile time — dispatch "
                            f"on the enqueue executor")
        for finding in lock_findings:
            yield finding


def _iter_own_nodes(stmts: List[ast.stmt]) -> Iterator[ast.AST]:
    """All nodes in these statements, not descending into nested
    function/class bodies (different execution context)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# -- rule 4: prng-key-reuse --------------------------------------------------

_KEY_MAKERS = {"jax.random.PRNGKey", "jax.random.key",
               "jax.random.split", "jax.random.fold_in"}
_NON_CONSUMING = {"PRNGKey", "key", "split", "fold_in",
                  "wrap_key_data", "key_data", "key_impl"}


class PrngKeyReuseRule(Rule):
    id = "prng-key-reuse"
    description = ("a jax.random key consumed by two sample calls "
                   "without an intervening split/fold_in (the draws "
                   "correlate)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        aliases = _import_aliases(tree)
        findings: List[Finding] = []
        seen_lines: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._scan_fn(node, ctx, aliases, findings,
                              seen_lines)
        return iter(findings)

    def _resolved(self, call: ast.Call,
                  aliases: Dict[str, str]) -> Optional[str]:
        name = dotted_name(call.func)
        if name is None:
            return None
        resolved = _resolve(name, aliases)
        # `self._jax.random.uniform` → normalize the stashed-module
        # spelling onto jax.random.
        parts = resolved.split(".")
        if "random" in parts[:-1] and any(
                p in ("jax", "_jax") for p in parts):
            return "jax.random." + parts[-1]
        if resolved.startswith("jax.random."):
            return resolved
        return None

    def _scan_fn(self, fn, ctx: FileContext,
                 aliases: Dict[str, str], findings: List[Finding],
                 seen_lines: Set[int]) -> None:
        # key var -> line of first consume.  Mutable container so the
        # If special-case below can swap branch-local copies in.
        used: Dict[str, int] = {}

        def fresh(targets: List[ast.AST]) -> None:
            for t in targets:
                if isinstance(t, ast.Name):
                    used.pop(t.id, None)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    fresh(list(t.elts))
                elif isinstance(t, ast.Starred):
                    fresh([t.value])

        def scan(stmts: List[ast.stmt], twice_for_loops: bool) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    value = stmt.value
                    if isinstance(value, ast.Call):
                        resolved = self._resolved(value, aliases)
                        if resolved in _KEY_MAKERS:
                            targets = (stmt.targets
                                       if isinstance(stmt, ast.Assign)
                                       else [stmt.target])
                            fresh(targets)
                for call in _TaintScan._stmt_calls(stmt):
                    resolved = self._resolved(call, aliases)
                    if resolved is None:
                        continue
                    bare = resolved.rsplit(".", 1)[-1]
                    if bare in _NON_CONSUMING:
                        continue
                    if not call.args or not isinstance(call.args[0],
                                                       ast.Name):
                        continue
                    key = call.args[0].id
                    if key in used:
                        if call.lineno not in seen_lines:
                            seen_lines.add(call.lineno)
                            findings.append(ctx.finding(
                                self.id, call,
                                f"key '{key}' already consumed by a "
                                f"jax.random call at line "
                                f"{used[key]} in '{fn.name}' — "
                                f"split/fold_in before sampling "
                                f"again, or the two draws correlate"))
                    else:
                        used[key] = call.lineno
                if isinstance(stmt, ast.If):
                    # Mutually exclusive branches: one draw per call
                    # whichever branch runs, so each scans against a
                    # private copy of the entry state; the exits
                    # merge (a key consumed on EITHER path counts as
                    # consumed after the If).
                    entry = dict(used)
                    branch_states = []
                    for body in (stmt.body, stmt.orelse):
                        used.clear()
                        used.update(entry)
                        scan(body, twice_for_loops)
                        branch_states.append(dict(used))
                    used.clear()
                    for state in branch_states:
                        for key, line in state.items():
                            used.setdefault(key, line)
                    continue
                for body in _TaintScan._child_bodies(stmt):
                    # Loop bodies run twice so a key consumed once
                    # per iteration without a re-split is caught.
                    if isinstance(stmt, (ast.For, ast.AsyncFor,
                                         ast.While)) \
                            and twice_for_loops:
                        scan(body, False)
                    scan(body, twice_for_loops)

        scan(fn.body, True)
