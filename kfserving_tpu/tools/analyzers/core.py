"""kfslint core: findings, pragmas, baseline, and the file walker.

Every rule is a stdlib-`ast` visitor producing `Finding`s with a
stable (rule, path, snippet) identity.  The framework owns everything
rules share:

- **pragmas** — `# kfslint: disable=<rule>[,<rule>...] <justification>`
  on the *finding's line* suppresses exactly those rules on exactly
  that line (comments are located with `tokenize`, so a pragma-shaped
  string literal never suppresses anything).  Scoping is deliberately
  line-tight: a pragma cannot blanket a function or file, so every
  deliberate violation carries its justification next to the code it
  excuses.
- **baseline** — a committed JSON list of known findings
  (`baseline.json` next to this package).  Findings matching a
  baseline entry don't fail the run; a baseline entry whose finding no
  longer exists is *stale* and FAILS the run (a fixed defect must be
  removed from the baseline, or the baseline rots into a blanket
  waiver).  Matching is by (rule, path, snippet) — line-number churn
  from unrelated edits never invalidates the baseline.
- **the walker** — `.py` files under the given roots, skipping
  `__pycache__` and generated protobuf modules.

Rules implement `check(tree, ctx)` (per file) and optionally
`finalize()` (tree-level cross-file checks, e.g. fault-site coverage).
"""

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

_PRAGMA_RE = re.compile(r"#\s*kfslint:\s*disable=([\w,\-]+)")

# Generated modules are not hand-maintained; their style is the
# generator's problem, and protobuf output trips no serving rules.
_SKIP_FILE_RE = re.compile(r"_pb2(_grpc)?\.py$")

# Golden lint fixtures FIRE by design — scanning them would demand
# baselining deliberate violations.  Their tests analyze them one
# file at a time, which bypasses this prune.
_SKIP_DIR_NAMES = {"__pycache__", "fixtures"}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # posix path as given to the walker
    line: int          # 1-based line of the offending node
    message: str
    snippet: str = ""  # stripped source line (baseline identity)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)


@dataclass
class FileContext:
    """Everything a rule may want about the file under analysis."""
    path: str
    source: str
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(rule=rule, path=self.path, line=line,
                       message=message, snippet=self.snippet(line))


class Rule:
    """One analysis rule.  Subclasses set `id`/`description` and yield
    findings from `check`; tree-level rules may also yield from
    `finalize` after every file has been seen."""

    id: str = ""
    description: str = ""

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finalize(self) -> Iterator[Finding]:
        return iter(())


# -- pragmas ----------------------------------------------------------------

def pragma_lines(source: str) -> Dict[int, Set[str]]:
    """{line: {rule, ...}} for every kfslint pragma comment.

    Two placements, both line-scoped:

    - trailing (``stmt  # kfslint: disable=r``) suppresses on the
      comment's own line;
    - standalone (a comment-only line) suppresses on the NEXT code
      line, skipping blank and comment-only lines — so a pragma can
      head a wrapped comment block above the statement it excuses.

    Tokenize-based so only real comments count; a source file that
    fails tokenization (it already parsed, so this is rare) falls back
    to a line-regex scan rather than silently losing its pragmas.
    """
    lines = source.splitlines()

    def _is_code(idx0: int) -> bool:
        stripped = lines[idx0].strip()
        return bool(stripped) and not stripped.startswith("#")

    def _target(line: int, col: int) -> int:
        if lines[line - 1][:col].strip():
            return line  # trailing: the statement shares the line
        for nxt in range(line, len(lines)):
            if _is_code(nxt):
                return nxt + 1
        return 0  # pragma at EOF: nothing to suppress

    pragmas: Dict[int, Set[str]] = {}

    def _add(line: int, col: int, text: str) -> None:
        m = _PRAGMA_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",")
                     if r.strip()}
            pragmas.setdefault(_target(line, col),
                               set()).update(rules)

    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                _add(tok.start[0], tok.start[1], tok.string)
    except (tokenize.TokenError, IndentationError):
        for i, line in enumerate(source.splitlines(), start=1):
            if "#" in line:
                _add(i, line.index("#"), line)
    return pragmas


# -- per-file analysis ------------------------------------------------------

def analyze_source(source: str, path: str, rules: Iterable[Rule],
                   respect_pragmas: bool = True) -> List[Finding]:
    """Run `rules` over one file's source.  A syntax error becomes a
    `parse-error` finding (an unparseable file in the serving tree is
    itself a defect, not a reason to skip analysis silently)."""
    ctx = FileContext(path=path, source=source)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rule="parse-error", path=path,
                        line=e.lineno or 0,
                        message=f"file does not parse: {e.msg}",
                        snippet=ctx.snippet(e.lineno or 0))]
    findings: List[Finding] = []
    suppress = pragma_lines(source) if respect_pragmas else {}
    for rule in rules:
        for f in rule.check(tree, ctx):
            if f.rule in suppress.get(f.line, ()):
                continue
            findings.append(f)
    return findings


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for root in paths:
        if not os.path.exists(root):
            # A typo'd path must not scan zero files and pass as
            # "clean".
            raise FileNotFoundError(f"no such file or directory: "
                                    f"{root!r}")
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIR_NAMES)
            for name in sorted(filenames):
                if name.endswith(".py") \
                        and not _SKIP_FILE_RE.search(name):
                    yield os.path.join(dirpath, name)


_repo_root_cache: List[Optional[str]] = []


def _repo_root() -> Optional[str]:
    """The checkout root (the installed package's parent) — lazy and
    cached; None when the package can't be located."""
    if not _repo_root_cache:
        try:
            import kfserving_tpu
            _repo_root_cache.append(os.path.dirname(os.path.dirname(
                os.path.abspath(kfserving_tpu.__file__))))
        except Exception:
            _repo_root_cache.append(None)
    return _repo_root_cache[0]


def normalize_path(path: str) -> str:
    """Stable finding/baseline path identity, posix separators.
    Paths inside the checkout normalize relative to the REPO ROOT —
    not the CWD — so the committed baseline (keyed on
    'benchmarks/...', 'kfserving_tpu/...') matches however and from
    wherever the run was spelled.  Paths outside the checkout fall
    back to CWD-relative."""
    abspath = os.path.abspath(path)
    root = _repo_root()
    if root is not None \
            and abspath.startswith(root.rstrip(os.sep) + os.sep):
        return os.path.relpath(abspath, root).replace(os.sep, "/")
    return os.path.relpath(abspath).replace(os.sep, "/")


def analyze_paths(paths: Iterable[str], rules: List[Rule],
                  respect_pragmas: bool = True) -> List[Finding]:
    sources: Dict[str, str] = {}
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            sources[normalize_path(path)] = fh.read()
    return analyze_snippets(sources, rules,
                            respect_pragmas=respect_pragmas)


def analyze_snippets(sources: Dict[str, str], rules: List[Rule],
                     respect_pragmas: bool = True) -> List[Finding]:
    """The per-file + finalize + pragma pipeline over in-memory
    sources ({path: source}).  `analyze_paths` delegates here after
    reading and path-normalizing; tests and tools can call it
    directly without touching disk.  finalize() findings (cross-file
    rules) honor pragmas too — a helper-reached blocking call is
    suppressed at its call-site line like any direct finding."""
    findings: List[Finding] = []
    pragmas_by_path = {
        path: (pragma_lines(src) if respect_pragmas else {})
        for path, src in sources.items()}
    for path, src in sources.items():
        for f in analyze_source(src, path, rules,
                                respect_pragmas=False):
            if f.rule not in pragmas_by_path[path].get(f.line, ()):
                findings.append(f)
    for rule in rules:
        for f in rule.finalize():
            if f.rule in pragmas_by_path.get(f.path, {}).get(f.line,
                                                             ()):
                continue
            findings.append(f)
    return findings


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> List[Dict[str, str]]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, list):
        raise ValueError(f"baseline {path}: expected a JSON list")
    return data


def save_baseline(path: str, findings: List[Finding]) -> None:
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "snippet": f.snippet, "message": f.message}
               for f in sorted(findings,
                               key=lambda f: (f.path, f.line, f.rule))]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(entries, fh, indent=2)
        fh.write("\n")


def apply_baseline(findings: List[Finding],
                   baseline: List[Dict[str, str]]
                   ) -> Tuple[List[Finding], List[Dict[str, str]]]:
    """Split into (new findings, stale baseline entries).

    Each baseline entry consumes at most one matching live finding
    (two identical snippets need two entries), so the baseline can
    never grow looser than what was committed.
    """
    budget: Dict[Tuple[str, str, str], int] = {}
    for entry in baseline:
        key = (entry.get("rule", ""), entry.get("path", ""),
               entry.get("snippet", ""))
        budget[key] = budget.get(key, 0) + 1
    new: List[Finding] = []
    for f in findings:
        key = f.key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            new.append(f)
    stale: List[Dict[str, str]] = []
    remaining = dict(budget)
    for entry in baseline:
        key = (entry.get("rule", ""), entry.get("path", ""),
               entry.get("snippet", ""))
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            stale.append(entry)
    return new, stale


# -- shared scoping policy --------------------------------------------------

def is_test_function(name: str) -> bool:
    """`test*` functions are harnesses: each drives a private event
    loop with no other traffic on it, and legitimately does setup I/O
    and device fetches to assert on results.  Event-loop *throughput*
    rules (async-blocking, host-sync, blocking-dispatch) skip them —
    stalling a loop nobody shares is not the defect class.  Liveness
    and correctness rules (spin-loop, prng-key-reuse, the discipline
    pair) stay in force: a livelocked test hangs CI exactly like a
    livelocked scheduler hangs serving."""
    return name.startswith("test")


# -- shared AST helpers -----------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_body_nodes(stmts: Iterable[ast.stmt],
                    skip_nested_defs: bool = True) -> Iterator[ast.AST]:
    """Walk statements, optionally NOT descending into nested
    function/class definitions (their bodies run in a different
    execution context than the enclosing async frame)."""
    stack: List[ast.AST] = list(stmts)
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            ast.ClassDef)
    while stack:
        node = stack.pop()
        yield node
        # A nested def is yielded (it IS a statement of this body) but
        # never expanded — its inner statements belong to a different
        # execution context.
        if skip_nested_defs and isinstance(node, skip):
            continue
        stack.extend(ast.iter_child_nodes(node))


def contains_await(stmts: Iterable[ast.stmt]) -> bool:
    """True if the statements await anything (Await / async for /
    async with), ignoring nested function bodies."""
    for node in iter_body_nodes(stmts):
        if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
    return False
