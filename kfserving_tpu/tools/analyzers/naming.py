"""Metric family naming rules — the single authority.

These are the house conventions for every exported metric family.
Two consumers apply them to the SAME rule code:

- `tools/check_metrics.py` lints the *runtime* view: the families a
  booted server actually registered and rendered (catches dynamic
  names, duplicate declarations, out-of-range ratio samples);
- the kfslint `metric-name` rule lints the *static* view: every
  string-literal family name passed to `REGISTRY.counter/gauge/
  histogram(...)` anywhere in the tree (catches misnamed families on
  code paths no smoke test happens to execute).

Keeping one implementation here means a new convention lands in both
tiers at once — the pre-PR-11 state, where check_metrics owned a
private copy, is exactly how the static and runtime twins drift.
"""

from typing import List

PREFIX = "kfserving_tpu_"
# Count units (_tokens, _blocks, _hits) joined the ladder with the
# cache/attribution families (ISSUE 13): token-count, block-count, and
# hits-per-entry histograms are distributions over discrete units, and
# forcing a time/size suffix onto them would lie about the unit.
UNIT_SUFFIXES = ("_ms", "_seconds", "_bytes", "_ratio", "_per_second",
                 "_tokens", "_blocks", "_hits")


def family_name_problems(name: str, kind: str) -> List[str]:
    """Naming problems for one family declaration.

    `kind` is "counter" | "gauge" | "histogram" (unknown kinds get the
    kind-independent checks only).
    """
    problems: List[str] = []
    if not name.startswith(PREFIX):
        problems.append(f"{name}: missing the {PREFIX!r} prefix")
    if kind == "counter" and not name.endswith("_total"):
        problems.append(f"{name}: counters must end in _total")
    if kind != "counter" and name.endswith("_total"):
        problems.append(
            f"{name}: _total suffix is reserved for counters "
            f"(is a {kind})")
    if "_milliseconds" in name or "_millis" in name:
        problems.append(f"{name}: spell milliseconds as _ms")
    if kind == "histogram" and not name.endswith(UNIT_SUFFIXES):
        problems.append(
            f"{name}: histograms must carry a unit suffix "
            f"({', '.join(UNIT_SUFFIXES)})")
    return problems
