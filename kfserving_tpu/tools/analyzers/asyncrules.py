"""kfslint concurrency rules — each one a landed defect class.

Every rule here is derived from a bug this repo actually shipped and
then fixed (see ISSUE 11 / CHANGES.md):

- `async-blocking`: a blocking call (`time.sleep`, `requests.*`,
  subprocess/socket waits) inside an `async def` freezes the whole
  event loop for its duration — every live stream, health probe, and
  admission decision stalls behind it.
- `spin-loop`: a `while` loop in an `async def` with no `await` /
  `async for` / `async with` in its body never yields to the loop;
  if its exit condition is flipped by another coroutine, it livelocks
  the process (the PR 5 growth-HOLD bug).
- `await-under-lock`: an `await` while holding a `threading` lock
  parks the lock across an arbitrary suspension — any engine worker
  thread (or the loop itself, re-entering) that wants the lock now
  waits on scheduler whim (the PR 5 chain-digest-hoist class).
- `cancellation-safety`: awaiting between acquiring a pooled resource
  and entering the `try/finally` (or `except CancelledError`) that
  releases it means a cancellation at that await orphans the resource
  (the PR 7 standby-pop leak class).

All four analyze `async def` bodies wherever they appear — including
async defs nested inside sync functions — and none descend into
nested `def`/`lambda` bodies (those run in whatever context calls
them, typically an executor, and get their own visit if async).
"""

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from kfserving_tpu.tools.analyzers.core import (
    FileContext,
    Finding,
    Rule,
    contains_await,
    dotted_name,
    is_test_function,
    iter_body_nodes,
)


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """{local name: canonical dotted name} from import statements, so
    `from time import sleep as zz` still resolves to time.sleep."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = \
                    f"{node.module}.{a.name}"
    return aliases


def _resolve(call_name: str, aliases: Dict[str, str]) -> str:
    """Canonicalize a call's dotted name through the import aliases."""
    head, sep, rest = call_name.partition(".")
    full = aliases.get(head, head)
    return full + sep + rest if sep else full


def iter_async_functions(tree: ast.Module
                         ) -> Iterator[ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            yield node


# -- rule 1: async-blocking -------------------------------------------------

_BLOCKING_EXACT = {
    "time.sleep",
    "os.system",
    "os.wait", "os.waitpid",
    "socket.create_connection", "socket.getaddrinfo",
    "urllib.request.urlopen",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.getoutput",
    "subprocess.getstatusoutput",
    "shutil.copyfile", "shutil.copytree", "shutil.rmtree",
    # Blocking file I/O: a cold page-cache read (or an fsync-heavy
    # write) holds the loop for disk time, and every live stream
    # pays it.
    "open",
    "json.load", "json.dump",
    "pickle.load", "pickle.dump",
    "os.replace", "os.rename", "os.makedirs",
    "tempfile.mkdtemp",
    "numpy.load", "numpy.save", "numpy.fromfile",
}
_REQUESTS_VERBS = {"get", "post", "put", "delete", "head", "patch",
                   "options", "request"}


def _blocking_primitive(node: ast.Call,
                        aliases: Dict[str, str]) -> Optional[str]:
    name = dotted_name(node.func)
    if name is None:
        return None
    resolved = _resolve(name, aliases)
    if resolved in _BLOCKING_EXACT:
        return resolved
    if resolved.startswith("requests.") \
            and resolved.split(".", 1)[1] in _REQUESTS_VERBS:
        return resolved
    return None


def _bare_call_name(node: ast.Call) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class AsyncBlockingRule(Rule):
    """Direct blocking calls in async bodies, plus one-hop-at-a-time
    helper resolution: a *sync* function whose body contains a
    blocking primitive — or calls another blocking sync function — is
    itself blocking, and an async def calling it is flagged.  Helper
    matching is by bare name and gated on the name being defined
    EXACTLY ONCE in the scanned tree (a `load` defined 18 times tells
    us nothing; a `_persist_credentials` defined once tells us
    everything), which keeps the interprocedural pass from guessing.

    Two shapes are exempt from the helper pass:

    - executor offloads — `loop.run_in_executor(...)` /
      `asyncio.to_thread(...)` schedule work off-loop, and
      `functools.partial(...)` only binds arguments; none of the
      three blocks even when the scanned tree contains a same-named
      fake (a test double's `run_in_executor` calling the fn inline
      must not poison every real offload in the tree).  A blocking
      callable passed BY REFERENCE through them never fires; a call
      evaluated in the argument list (`to_thread(self._load())`)
      still does.
    - awaited calls — `await call(payload)` proves the callee is a
      coroutine function, so matching it to a same-named *sync* def
      elsewhere in the tree is definitionally wrong (the PR 14
      `retry.call` false-positive class).
    """

    id = "async-blocking"
    description = ("blocking call (time.sleep, requests.*, file/"
                   "subprocess/socket I/O) on an event-loop path")

    # Offload/binding vocabulary: these schedule or curry, never
    # block, whatever a same-named def in the scanned tree does.
    _OFFLOAD_NAMES = {"run_in_executor", "to_thread", "partial"}

    def __init__(self):
        # bare def name -> count across the scanned tree (sync+async)
        self._def_count: Dict[str, int] = {}
        # sync def name -> (primitive or None, {bare names it calls})
        self._sync_defs: Dict[str, Tuple[Optional[str], Set[str]]] = {}
        self._def_loc: Dict[str, str] = {}
        # deferred helper-call sites awaiting the cross-file index:
        # (path, line, snippet, async fn name, bare callee name)
        self._candidates: List[Tuple[str, int, str, str, str]] = []

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        aliases = _import_aliases(tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._def_count[node.name] = \
                    self._def_count.get(node.name, 0) + 1
                self._def_loc.setdefault(
                    node.name, f"{ctx.path}:{node.lineno}")
            if isinstance(node, ast.FunctionDef):
                primitive, calls = None, set()
                for n in iter_body_nodes(node.body):
                    if isinstance(n, ast.Call):
                        p = _blocking_primitive(n, aliases)
                        if p and primitive is None:
                            primitive = p
                        bare = _bare_call_name(n)
                        if bare and bare not in self._OFFLOAD_NAMES:
                            calls.add(bare)
                if node.name not in self._sync_defs \
                        or primitive is not None:
                    self._sync_defs[node.name] = (primitive, calls)
        for fn in iter_async_functions(tree):
            if is_test_function(fn.name):
                # A test's loop has no other traffic to stall; see
                # core.is_test_function for the scoping policy.
                continue
            awaited = {id(n.value) for n in iter_body_nodes(fn.body)
                       if isinstance(n, ast.Await)
                       and isinstance(n.value, ast.Call)}
            for node in iter_body_nodes(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                primitive = _blocking_primitive(node, aliases)
                if primitive is not None:
                    yield ctx.finding(
                        self.id, node,
                        f"blocking call {primitive}() inside "
                        f"'async def {fn.name}' stalls the event "
                        f"loop (run it in an executor)")
                    continue
                bare = _bare_call_name(node)
                # An awaited callable is a coroutine function — a
                # same-named SYNC def elsewhere cannot be this
                # callee.  Offload/binding calls never block.
                if bare and id(node) not in awaited \
                        and bare not in self._OFFLOAD_NAMES:
                    line = node.lineno
                    self._candidates.append(
                        (ctx.path, line, ctx.snippet(line), fn.name,
                         bare))

    def finalize(self) -> Iterator[Finding]:
        # Fixpoint over uniquely-named sync defs: blocking spreads
        # from primitives up through call chains one hop per pass.
        blocking: Dict[str, str] = {
            name: prim for name, (prim, _calls)
            in self._sync_defs.items() if prim is not None}
        changed = True
        while changed:
            changed = False
            for name, (_prim, calls) in self._sync_defs.items():
                if name in blocking:
                    continue
                for callee in calls:
                    if callee in blocking \
                            and self._def_count.get(callee) == 1:
                        blocking[name] = (
                            f"{callee}() -> {blocking[callee]}")
                        changed = True
                        break
        for path, line, snippet, async_fn, bare in self._candidates:
            if bare in blocking and self._def_count.get(bare) == 1:
                via = self._def_loc.get(bare, "?")
                yield Finding(
                    rule=self.id, path=path, line=line,
                    message=(f"'async def {async_fn}' calls sync "
                             f"helper {bare}() ({via}) which blocks "
                             f"via {blocking[bare]} — move the call "
                             f"to an executor"),
                    snippet=snippet)


# -- rule 2: spin-loop ------------------------------------------------------

class SpinLoopRule(Rule):
    id = "spin-loop"
    description = ("while loop in an async def whose body never "
                   "awaits (event-loop starvation / livelock)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        for fn in iter_async_functions(tree):
            for node in iter_body_nodes(fn.body):
                if isinstance(node, ast.While) \
                        and not contains_await(node.body) \
                        and not any(isinstance(n, ast.Await)
                                    for n in ast.walk(node.test)):
                    yield ctx.finding(
                        self.id, node,
                        f"while loop in 'async def {fn.name}' has no "
                        f"await in its body — if its exit condition "
                        f"is flipped by another coroutine this "
                        f"livelocks the loop")


# -- rule 3: await-under-lock -----------------------------------------------

_THREADING_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
_ASYNCIO_FACTORIES = {
    "asyncio.Lock", "asyncio.Condition", "asyncio.Semaphore",
    "asyncio.BoundedSemaphore", "asyncio.Event",
}
# Whole snake_case segments only: `_block_lock` is lockish,
# `block_table` (the dominant "block" noun in this codebase) is not.
_LOCKISH_SEGMENTS = {"lock", "rlock", "wlock", "mutex"}


def _lockish_name(name: str) -> bool:
    return any(seg in _LOCKISH_SEGMENTS
               for seg in name.lower().split("_"))


def _classify_locks(tree: ast.Module,
                    aliases: Dict[str, str]) -> Dict[str, Set[str]]:
    """{bare name: {"threading"|"asyncio", ...}} from every
    assignment / annotation whose RHS or type is a known lock factory.
    Attribute targets collapse to their attr name (`self._lock` →
    `_lock`) — file-local resolution is deliberate; cross-module lock
    identity is the pragma's job."""
    kinds: Dict[str, Set[str]] = {}

    def classify_value(node: Optional[ast.AST]) -> Optional[str]:
        if isinstance(node, ast.Call):
            node = node.func
        name = dotted_name(node) if node is not None else None
        if name is None:
            return None
        resolved = _resolve(name, aliases)
        if resolved in _THREADING_FACTORIES:
            return "threading"
        if resolved in _ASYNCIO_FACTORIES:
            return "asyncio"
        return None

    def record(target: ast.AST, kind: Optional[str]) -> None:
        if kind is None:
            return
        if isinstance(target, ast.Attribute):
            kinds.setdefault(target.attr, set()).add(kind)
        elif isinstance(target, ast.Name):
            kinds.setdefault(target.id, set()).add(kind)

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            kind = classify_value(node.value)
            for target in node.targets:
                record(target, kind)
        elif isinstance(node, ast.AnnAssign):
            kind = classify_value(node.value) \
                or classify_value(node.annotation)
            record(node.target, kind)
        elif isinstance(node, ast.arg) and node.annotation is not None:
            kind = classify_value(node.annotation)
            if kind:
                kinds.setdefault(node.arg, set()).add(kind)
    return kinds


class AwaitUnderLockRule(Rule):
    id = "await-under-lock"
    description = ("await while holding a threading lock (sync "
                   "`with <lock>:` containing an await)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        aliases = _import_aliases(tree)
        lock_kinds = _classify_locks(tree, aliases)
        for fn in iter_async_functions(tree):
            for node in iter_body_nodes(fn.body):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                    base = None
                    if isinstance(expr, ast.Attribute):
                        base = expr.attr
                    elif isinstance(expr, ast.Name):
                        base = expr.id
                    if base is None:
                        continue
                    kinds = lock_kinds.get(base, set())
                    # Unclassified names still count when they LOOK
                    # like a lock: a sync `with` on an asyncio.Lock
                    # raises at runtime, so a lock-named object in a
                    # sync with-statement is a thread lock in practice.
                    threadlock = kinds == {"threading"} or (
                        not kinds and _lockish_name(base))
                    if threadlock and contains_await(node.body):
                        yield ctx.finding(
                            self.id, node,
                            f"await inside `with {base}:` in 'async "
                            f"def {fn.name}' holds a thread lock "
                            f"across a suspension point (deadlock/"
                            f"convoy risk — release before awaiting)")
                        break


# -- rule 4: cancellation-safety --------------------------------------------

_ACQUIRE_ATTRS = {"acquire", "pop_standby", "obtain_standby",
                  "checkout", "lease", "reserve"}
_POOLED_GET_ATTRS = {"get", "pop"}
_POOLED_RECEIVER = re.compile(
    r"queue|pool|standby|free|idle|avail", re.IGNORECASE)
_CANCELLED_NAMES = {"CancelledError", "BaseException"}


def _acquire_call(stmt: ast.stmt) -> Optional[str]:
    """If `stmt` is `x = await <pooled acquire>(...)`, return a label
    for the acquired resource, else None."""
    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        value = stmt.value
    else:
        return None
    if not isinstance(value, ast.Await) \
            or not isinstance(value.value, ast.Call):
        return None
    func = value.value.func
    if isinstance(func, ast.Attribute):
        recv = dotted_name(func.value) or ""
        # `self._obtain_standby` matches `obtain_standby`: private
        # naming must not hide an acquire from the rule.
        attr = func.attr.lstrip("_")
        if attr in _ACQUIRE_ATTRS:
            return f"{recv}.{func.attr}" if recv else func.attr
        if attr in _POOLED_GET_ATTRS and _POOLED_RECEIVER.search(
                recv.rsplit(".", 1)[-1]):
            return f"{recv}.{func.attr}"
    elif isinstance(func, ast.Name) and "acquire" in func.id.lower():
        return func.id
    return None


def _protective(node: ast.Try) -> bool:
    """Does this try release on cancellation — a finally, or an
    except clause catching CancelledError/BaseException?"""
    if node.finalbody:
        return True
    for handler in node.handlers:
        types = [handler.type]
        if isinstance(handler.type, ast.Tuple):
            types = list(handler.type.elts)
        for t in types:
            name = dotted_name(t) if t is not None else None
            if name and name.rsplit(".", 1)[-1] in _CANCELLED_NAMES:
                return True
    return False


def _stmt_awaits(stmt: ast.stmt) -> bool:
    return contains_await([stmt])


class CancellationSafetyRule(Rule):
    id = "cancellation-safety"
    description = ("await between a pooled-resource acquire and the "
                   "try/finally that releases it (cancellation "
                   "orphans the resource)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Finding]:
        findings: List[Finding] = []
        for fn in iter_async_functions(tree):
            self._scan_block(fn, fn.body, False, ctx, findings)
        return iter(findings)

    def _scan_block(self, fn: ast.AsyncFunctionDef,
                    stmts: List[ast.stmt], protected: bool,
                    ctx: FileContext,
                    findings: List[Finding]) -> None:
        for i, stmt in enumerate(stmts):
            label = None if protected else _acquire_call(stmt)
            if label is not None:
                for later in stmts[i + 1:]:
                    if isinstance(later, ast.Try) \
                            and _protective(later):
                        break
                    if _stmt_awaits(later):
                        findings.append(ctx.finding(
                            self.id, stmt,
                            f"'{label}' acquired in 'async def "
                            f"{fn.name}' but an await runs before "
                            f"the try/finally (or CancelledError "
                            f"handler) that would release it — a "
                            f"cancellation there orphans the "
                            f"resource"))
                        break
            for block, child_protected in self._child_blocks(
                    stmt, protected):
                self._scan_block(fn, block, child_protected, ctx,
                                 findings)

    @staticmethod
    def _child_blocks(stmt: ast.stmt, protected: bool
                      ) -> Iterator[Tuple[List[ast.stmt], bool]]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Try):
            inner = protected or _protective(stmt)
            yield stmt.body, inner
            for handler in stmt.handlers:
                yield handler.body, protected
            # A finally covers the else-block's awaits too; handlers
            # do not (exceptions raised in else bypass them).
            yield stmt.orelse, protected or bool(stmt.finalbody)
            yield stmt.finalbody, protected
        elif isinstance(stmt, (ast.If, ast.While, ast.For,
                               ast.AsyncFor)):
            yield stmt.body, protected
            yield stmt.orelse, protected
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            yield stmt.body, protected
