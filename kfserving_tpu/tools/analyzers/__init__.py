"""kfslint — AST-based concurrency & serving-discipline analyzer.

Usage (CLI)::

    python -m kfserving_tpu.tools.analyzers [paths ...]
    kfs-lint [paths ...]                      # console-script alias

With no paths it analyzes the installed ``kfserving_tpu`` package.
Exit 0 means: zero findings that are neither pragma-suppressed nor in
the committed baseline, AND zero stale baseline entries.

Rules (see ``asyncrules.py`` / ``discipline.py`` / ``devicerules.py``
for the defect class each one encodes): the concurrency four
(``async-blocking``, ``spin-loop``, ``await-under-lock``,
``cancellation-safety``), the serving-discipline pair
(``fault-site``, ``metric-name``), and the XLA/JAX device tier
(``host-sync``, ``jit-recompile-hazard``, ``blocking-dispatch``,
``prng-key-reuse``).

Suppression: ``# kfslint: disable=<rule>[,<rule>]  <justification>``
on the finding's line.  Known legacy findings live in
``baseline.json`` next to this package; a baseline entry whose
finding disappeared fails the run as stale.
"""

import os
from typing import List

from kfserving_tpu.tools.analyzers.asyncrules import (
    AsyncBlockingRule,
    AwaitUnderLockRule,
    CancellationSafetyRule,
    SpinLoopRule,
)
from kfserving_tpu.tools.analyzers.core import (
    Finding,
    Rule,
    analyze_paths,
    analyze_snippets,
    analyze_source,
    apply_baseline,
    load_baseline,
    save_baseline,
)
from kfserving_tpu.tools.analyzers.devicerules import (
    BlockingDispatchRule,
    HostSyncRule,
    JitRecompileHazardRule,
    PrngKeyReuseRule,
)
from kfserving_tpu.tools.analyzers.discipline import (
    FaultSiteRule,
    MetricNameRule,
)

__all__ = [
    "Finding", "Rule", "analyze_paths", "analyze_snippets",
    "analyze_source", "apply_baseline", "load_baseline",
    "save_baseline", "default_rules", "rule_ids",
    "default_baseline_path", "default_target", "default_targets",
]


def default_rules() -> List[Rule]:
    """Fresh rule instances (rules carry per-run state; never share
    instances across runs)."""
    return [AsyncBlockingRule(), SpinLoopRule(), AwaitUnderLockRule(),
            CancellationSafetyRule(), FaultSiteRule(),
            MetricNameRule(), HostSyncRule(),
            JitRecompileHazardRule(), BlockingDispatchRule(),
            PrngKeyReuseRule()]


def rule_ids() -> List[str]:
    return [r.id for r in default_rules()]


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def default_target() -> str:
    """The installed package root — what a bare `kfs-lint` analyzes."""
    import kfserving_tpu
    return os.path.dirname(os.path.abspath(kfserving_tpu.__file__))


def default_targets() -> List[str]:
    """Everything a bare `kfs-lint` (and the fast-tier gate) scans:
    the package tree plus the `benchmarks/` and `tests/` trees living
    next to it when present — bench drivers and tests run the same
    event-loop/device disciplines the package does, and a spin-loop
    in a test hangs CI exactly like one in the scheduler would."""
    pkg = default_target()
    roots = [pkg]
    repo = os.path.dirname(pkg)
    # Only a repo checkout carries its pyproject next to the package;
    # in site-packages a sibling `tests/` dir is some OTHER
    # distribution's packaging accident, not ours to lint.
    if os.path.isfile(os.path.join(repo, "pyproject.toml")):
        for extra in ("benchmarks", "tests"):
            path = os.path.join(repo, extra)
            if os.path.isdir(path):
                roots.append(path)
    return roots
