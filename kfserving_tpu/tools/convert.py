"""Checkpoint conversion: torch/HF artifacts -> servable model dirs.

The reference serves torch models via pytorchserver and everything else
via opaque third-party servers; the TPU build's fast path is the jax
predictor, so migration needs the reference user's *weights* to cross
over.  This tool maps HF-layout torch state dicts onto the first-party
Flax zoo (models/bert.py, models/resnet.py) tensor-for-tensor:

- BERT (HF BertForMaskedLM layout, `bert.*` / `cls.*` keys): q/k/v
  kernels fold to DenseGeneral [H, heads, dH] layout, MLM head keeps
  the tied-embedding decoder.  The emitted config sets
  gelu_approximate=false (HF "gelu" is erf-exact).
- ResNet-50 (HF ResNetForImageClassification layout, `resnet.*` /
  `classifier.*` keys): OIHW conv weights transpose to HWIO,
  BatchNorm running stats land in batch_stats.  The emitted config
  sets torch_padding=true (explicit pads, not SAME — a one-pixel
  shift otherwise).

CLI:
    python -m kfserving_tpu.tools.convert --arch bert \
        --torch_checkpoint pytorch_model.bin --out_dir DIR [--json k=v]

Parity is tested numerically against the torch implementations in
tests/test_convert.py (same inputs, logits allclose).
"""

import argparse
import json
import os
from typing import Any, Dict

import numpy as np


def _t(x) -> np.ndarray:
    """torch tensor -> float32 numpy."""
    return np.asarray(x.detach().cpu().numpy(), dtype=np.float32)


# -- BERT ---------------------------------------------------------------------
def bert_params_from_torch(state_dict: Dict[str, Any],
                           num_heads: int) -> Dict[str, Any]:
    """HF BertForMaskedLM state dict -> models/bert.py variables."""
    sd = {k: _t(v) for k, v in state_dict.items()
          if not k.endswith("num_batches_tracked")}

    def ln(prefix):
        return {"scale": sd[f"{prefix}.weight"],
                "bias": sd[f"{prefix}.bias"]}

    hidden = sd["bert.embeddings.word_embeddings.weight"].shape[1]
    head_dim = hidden // num_heads
    params: Dict[str, Any] = {
        "word_embeddings": {
            "embedding": sd["bert.embeddings.word_embeddings.weight"]},
        "position_embeddings": {
            "embedding": sd["bert.embeddings.position_embeddings.weight"]},
        "token_type_embeddings": {
            "embedding": sd["bert.embeddings.token_type_embeddings.weight"]},
        "embeddings_norm": ln("bert.embeddings.LayerNorm"),
        "mlm_transform": {
            "kernel": sd["cls.predictions.transform.dense.weight"].T,
            "bias": sd["cls.predictions.transform.dense.bias"]},
        "mlm_norm": ln("cls.predictions.transform.LayerNorm"),
        "mlm_bias": sd["cls.predictions.bias"],
    }
    i = 0
    while f"bert.encoder.layer.{i}.attention.self.query.weight" in sd:
        p = f"bert.encoder.layer.{i}"
        att = {}
        for name in ("query", "key", "value"):
            w = sd[f"{p}.attention.self.{name}.weight"]  # [H, H] (out,in)
            b = sd[f"{p}.attention.self.{name}.bias"]
            att[name] = {
                "kernel": w.T.reshape(hidden, num_heads, head_dim),
                "bias": b.reshape(num_heads, head_dim)}
        wo = sd[f"{p}.attention.output.dense.weight"]    # [H, H]
        att["out"] = {
            "kernel": wo.T.reshape(num_heads, head_dim, hidden),
            "bias": sd[f"{p}.attention.output.dense.bias"]}
        params[f"layer_{i}"] = {
            "attention": att,
            "attention_norm": ln(f"{p}.attention.output.LayerNorm"),
            "intermediate": {
                "kernel": sd[f"{p}.intermediate.dense.weight"].T,
                "bias": sd[f"{p}.intermediate.dense.bias"]},
            "output": {
                "kernel": sd[f"{p}.output.dense.weight"].T,
                "bias": sd[f"{p}.output.dense.bias"]},
            "output_norm": ln(f"{p}.output.LayerNorm"),
        }
        i += 1
    if i == 0:
        raise ValueError(
            "no bert.encoder.layer.* keys found — is this an HF "
            "BertForMaskedLM state dict?")
    return {"params": params}


# -- ResNet-50 ----------------------------------------------------------------
def _conv(w: np.ndarray) -> np.ndarray:
    """OIHW -> HWIO."""
    return w.transpose(2, 3, 1, 0)


def resnet50_params_from_torch(state_dict: Dict[str, Any]
                               ) -> Dict[str, Any]:
    """HF ResNetForImageClassification state dict -> models/resnet.py
    variables (params + batch_stats)."""
    sd = {k: _t(v) for k, v in state_dict.items()
          if not k.endswith("num_batches_tracked")}

    def bn(prefix):
        return ({"scale": sd[f"{prefix}.weight"],
                 "bias": sd[f"{prefix}.bias"]},
                {"mean": sd[f"{prefix}.running_mean"],
                 "var": sd[f"{prefix}.running_var"]})

    emb = "resnet.embedder.embedder"
    if f"{emb}.convolution.weight" not in sd:
        raise ValueError(
            "no resnet.embedder.* keys found — is this an HF "
            "ResNetForImageClassification state dict?")
    bn_p, bn_s = bn(f"{emb}.normalization")
    params: Dict[str, Any] = {
        "conv_init": {"kernel": _conv(sd[f"{emb}.convolution.weight"])},
        "bn_init": bn_p,
        "head": {"kernel": sd["classifier.1.weight"].T,
                 "bias": sd["classifier.1.bias"]},
    }
    stats: Dict[str, Any] = {"bn_init": bn_s}

    block = 0
    stage = 0
    while f"resnet.encoder.stages.{stage}.layers.0.layer.0." \
          f"convolution.weight" in sd:
        layer = 0
        while (f"resnet.encoder.stages.{stage}.layers.{layer}.layer.0."
               f"convolution.weight") in sd:
            p = f"resnet.encoder.stages.{stage}.layers.{layer}"
            bp: Dict[str, Any] = {}
            bs: Dict[str, Any] = {}
            for c in range(3):
                bp[f"Conv_{c}"] = {"kernel": _conv(
                    sd[f"{p}.layer.{c}.convolution.weight"])}
                nb, ns = bn(f"{p}.layer.{c}.normalization")
                bp[f"BatchNorm_{c}"] = nb
                bs[f"BatchNorm_{c}"] = ns
            if f"{p}.shortcut.convolution.weight" in sd:
                bp["conv_proj"] = {"kernel": _conv(
                    sd[f"{p}.shortcut.convolution.weight"])}
                nb, ns = bn(f"{p}.shortcut.normalization")
                bp["norm_proj"] = nb
                bs["norm_proj"] = ns
            params[f"BottleneckBlock_{block}"] = bp
            stats[f"BottleneckBlock_{block}"] = bs
            block += 1
            layer += 1
        stage += 1
    return {"params": params, "batch_stats": stats}


# -- entry --------------------------------------------------------------------
CONVERTERS = {
    "bert": lambda sd, kw: bert_params_from_torch(
        sd, num_heads=kw.get("num_heads", 12)),
    "resnet50": lambda sd, kw: resnet50_params_from_torch(sd),
}


def convert(arch: str, state_dict: Dict[str, Any], out_dir: str,
            arch_kwargs: Dict[str, Any] = None,
            config_extra: Dict[str, Any] = None) -> str:
    """Write a servable model dir (config.json + checkpoint.msgpack)."""
    from flax import serialization

    arch_kwargs = dict(arch_kwargs or {})
    if arch not in CONVERTERS:
        raise ValueError(
            f"no converter for {arch!r}; have {sorted(CONVERTERS)}")
    variables = CONVERTERS[arch](state_dict, arch_kwargs)
    # Geometry/activation flags that make the converted weights exact:
    if arch == "bert":
        arch_kwargs.setdefault("gelu_approximate", False)
    if arch == "resnet50":
        arch_kwargs.setdefault("torch_padding", True)
    os.makedirs(out_dir, exist_ok=True)
    cfg = {"architecture": arch, "arch_kwargs": arch_kwargs}
    cfg.update(config_extra or {})
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(cfg, f, indent=1)
    with open(os.path.join(out_dir, "checkpoint.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(variables))
    return out_dir


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Convert a torch/HF checkpoint into a jaxserver "
                    "model dir")
    p.add_argument("--arch", required=True, choices=sorted(CONVERTERS))
    p.add_argument("--torch_checkpoint", required=True,
                   help="path to a torch state dict (torch.save)")
    p.add_argument("--out_dir", required=True)
    p.add_argument("--arch_kwargs", default="{}", help="JSON dict")
    p.add_argument("--config_extra", default="{}",
                   help="JSON dict merged into config.json (batcher, "
                        "buckets, output mode, ...)")
    args = p.parse_args(argv)
    import torch

    state = torch.load(args.torch_checkpoint, map_location="cpu",
                       weights_only=True)
    convert(args.arch, state, args.out_dir,
            json.loads(args.arch_kwargs), json.loads(args.config_extra))
    print(f"wrote {args.out_dir}")


if __name__ == "__main__":
    main()
