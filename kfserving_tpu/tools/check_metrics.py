"""Metrics-exposition linter: keep every exported family well-formed.

PR 2/3 grew the metric surface to ~30 families fed from six layers;
nothing enforced the conventions that make the surface scrapeable and
greppable.  This tool lints every exported family against the house
rules and runs in the fast test tier, so a misnamed series fails CI
before it ships:

- every family name carries the `kfserving_tpu_` prefix;
- counters end in `_total` (and nothing else ends in `_total`);
- time/size-valued families carry a unit suffix (`_ms`, `_seconds`,
  `_bytes`, `_ratio`, `_per_second`) — and never a spelled-out
  `_milliseconds`;
- `_ratio`-suffixed gauges are bounded: every exported sample must sit
  in [0, 1] (a padding-waste or goodput "ratio" above 1 means the
  accounting is broken, and downstream alert math silently trusts the
  unit the suffix declares);
- no family is declared twice in one exposition (strict OpenMetrics
  parsers abort the whole scrape on a re-declared family);
- no family is registered under two kinds (the registry raises, but a
  private+global registry pair could still disagree — the lint
  catches the merged view).

Run standalone (`python -m kfserving_tpu.tools.check_metrics`) it
boots an in-process server, serves one smoke request, and lints the
full rendered scrape — exit 1 on any problem.
"""

import asyncio
import re
import sys
from typing import Dict, List

from kfserving_tpu.tools.analyzers.naming import (
    PREFIX,
    family_name_problems,
)


def lint_families(families: Dict[str, str]) -> List[str]:
    """Lint a {family name: kind} mapping (registry introspection).
    The naming rules live in `tools/analyzers/naming.py`, shared with
    kfslint's static `metric-name` rule — one rule set, two tiers."""
    problems: List[str] = []
    for name, kind in sorted(families.items()):
        problems.extend(family_name_problems(name, kind))
    return problems


def lint_exposition(text: str) -> List[str]:
    """Lint a rendered scrape: duplicate family declarations, the
    naming rules over every declared family, and prefix coverage of
    every sample line (declared or bare)."""
    problems: List[str] = []
    declared: Dict[str, str] = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) < 4:
                problems.append(f"malformed TYPE line: {line!r}")
                continue
            name, kind = parts[2], parts[3]
            if name in declared:
                problems.append(
                    f"{name}: declared twice (strict parsers abort "
                    "the whole scrape)")
            declared[name] = kind
            continue
        if not line or line.startswith("#"):
            continue
        sample = re.split(r"[{ ]", line, maxsplit=1)[0]
        if not sample.startswith(PREFIX):
            problems.append(
                f"sample {sample!r}: missing the {PREFIX!r} prefix")
        # Gauge-unit rule: a `_ratio` gauge promises [0, 1] — check
        # every sample value (gauge lines are `name[{labels}] value`;
        # gauges never carry exemplar suffixes).
        if declared.get(sample) == "gauge" \
                and sample.endswith("_ratio"):
            try:
                value = float(line.rsplit(" ", 1)[1])
            except (IndexError, ValueError):
                problems.append(
                    f"{sample}: unparseable gauge sample {line!r}")
                continue
            if not 0.0 <= value <= 1.0:  # NaN fails both bounds
                problems.append(
                    f"{sample}: _ratio gauge sample {value} outside "
                    f"[0, 1]")
    problems += lint_families(declared)
    return problems


async def smoke() -> List[str]:
    """Boot an in-process server, serve one request (populating the
    request/batcher/engine families), and lint the merged scrape plus
    both registries' introspection."""
    from kfserving_tpu.model.model import Model
    from kfserving_tpu.observability import REGISTRY
    from kfserving_tpu.server.app import ModelServer
    from kfserving_tpu.server.http import Request

    class _Probe(Model):
        def load(self):
            self.ready = True
            return True

        async def predict(self, request):
            return {"predictions": request["instances"]}

    server = ModelServer(http_port=0)
    probe = _Probe("metrics-probe")
    probe.load()
    server.register_model(probe)
    req = Request(method="POST",
                  path="/v1/models/metrics-probe:predict", query={},
                  headers={}, body=b'{"instances": [[1.0, 2.0]]}')
    req.path_params = {"name": "metrics-probe"}
    resp = await server._inference(req, "predict",
                                   server.dataplane.infer)
    # Populate the roofline families with representative values so the
    # lint always covers them (the probe model has no engine; a real
    # replica publishes these from its engine stats at scrape time).
    from kfserving_tpu.observability.profiling import roofline

    roofline.publish_gauges("metrics-probe", {
        "mfu": 0.42, "decode_mfu": 0.011, "prefill_mfu": 0.2,
        "achieved_tflops": 82.7, "achieved_decode_tflops": 2.1,
        "goodput_ratio": 0.97, "hbm_bw_util": 0.63,
        "bucket_pad_waste": {"b8": 0.25, "b8s128": 0.5},
        "prefill_bucket_pad_waste": {"s64": 0.11},
    })
    # Replica-lifecycle families (ISSUE 10): touched with
    # representative samples so the lint always covers the names,
    # label shapes, and unit suffixes the orchestrator/router emit.
    from kfserving_tpu.observability import metrics as obs

    obs.lifecycle_swaps_total().labels(
        mode="warm_standby", outcome="ok").inc()
    obs.lifecycle_swap_failures_total().labels(
        reason="activate_error").inc()
    obs.lifecycle_promotions_total().labels(
        trigger="process_exit", outcome="promoted").inc()
    for phase, ms in (("standby_spawn", 1800.0), ("activate", 650.0),
                      ("drain", 120.0), ("promote", 900.0)):
        obs.lifecycle_phase_ms().labels(phase=phase).observe(ms)
    obs.lifecycle_standby_pool().labels(
        component="default/probe/predictor").set(1.0)
    obs.router_swap_held_total().labels(outcome="served").inc()
    obs.router_swap_hold_ms().observe(42.0)
    obs.router_stream_failover_total().labels(
        model="metrics-probe").inc()
    obs.param_cache_total().labels(outcome="hit").inc()
    # Predictive control-loop families (ISSUE 12): decision counters,
    # the feed-forward sizing gauge, and the brownout trio.
    obs.autoscaler_tick_failures_total().inc()
    obs.autoscaler_decisions_total().labels(
        component="default/probe/predictor", action="pre_arm").inc()
    obs.autoscaler_predicted_replicas().labels(
        component="default/probe/predictor").set(3.0)
    obs.brownout_level().labels(model="metrics-probe").set(1.0)
    obs.brownout_shed_total().labels(
        model="metrics-probe", reason="priority").inc()
    obs.brownout_transitions_total().labels(
        model="metrics-probe", direction="enter").inc()
    # Cache & cost attribution families (ISSUE 13): prefix-index
    # lookups/evictions/reuse depth, the paged-pool `_ratio` gauges
    # (must be bounded [0, 1]), HBM residency, and the per-request
    # attribution histograms — touched with representative samples so
    # the lint always covers names, label shapes, and unit suffixes.
    obs.generator_prefix_lookups_total().labels(
        model="metrics-probe", outcome="hit").inc(3)
    obs.generator_prefix_lookups_total().labels(
        model="metrics-probe", outcome="miss").inc()
    obs.generator_prefix_lookups_total().labels(
        model="metrics-probe", outcome="host_hit").inc()
    obs.generator_prefill_tokens_saved_total().labels(
        model="metrics-probe").inc(384)
    # ISSUE 16: `capacity` split by fate — spilled to the host tier
    # vs dropped (the baseline / a failed spill).
    for cause in ("capacity_spilled", "capacity_dropped",
                  "index_invalidation", "zombie_deferral"):
        obs.generator_block_evictions_total().labels(
            model="metrics-probe", cause=cause).inc()
    obs.generator_prefix_reuse_depth_hits().labels(
        model="metrics-probe").observe(3)
    obs.generator_pool_occupancy_ratio().labels(
        model="metrics-probe").set(0.62)
    obs.generator_pool_fragmentation_ratio().labels(
        model="metrics-probe").set(0.18)
    obs.hbm_resident_bytes().labels(model="metrics-probe").set(2.1e9)
    obs.hbm_budget_bytes().set(12.0 * 1024**3)
    obs.hbm_evictions_total().labels(model="metrics-probe").inc()
    for phase, ms in (("prefill", 41.0), ("decode", 220.0)):
        obs.request_device_ms().labels(
            model="metrics-probe", phase=phase).observe(ms)
    obs.request_phase_tokens().labels(
        model="metrics-probe", phase="prefill").observe(128)
    obs.request_phase_tokens().labels(
        model="metrics-probe", phase="decode").observe(64)
    obs.request_held_blocks().labels(
        model="metrics-probe").observe(5)
    obs.request_cache_saved_tokens().labels(
        model="metrics-probe").observe(256)
    # Tiered KV residency families (ISSUE 16): host-tier occupancy,
    # spill/fault-back outcomes, tier evictions, fault-back latency,
    # and the per-request host-tier savings histogram (distinct from
    # the device-cache one just above) — representative samples so
    # names, label shapes, and unit suffixes always lint.
    obs.generator_kv_tier_blocks().labels(
        model="metrics-probe").set(48.0)
    obs.generator_kv_tier_occupancy_ratio().labels(
        model="metrics-probe").set(0.75)
    for outcome in ("spilled", "failed", "duplicate"):
        obs.generator_kv_tier_spills_total().labels(
            model="metrics-probe", outcome=outcome).inc()
    for outcome in ("faulted", "coalesced", "failed"):
        obs.generator_kv_tier_faultbacks_total().labels(
            model="metrics-probe", outcome=outcome).inc()
    obs.generator_kv_tier_faultback_ms().labels(
        model="metrics-probe").observe(3.2)
    for reason in ("capacity", "skipped_inflight", "faultback_failed"):
        obs.generator_kv_tier_evictions_total().labels(
            model="metrics-probe", reason=reason).inc()
    obs.generator_kv_tier_tokens_saved_total().labels(
        model="metrics-probe").inc(512)
    obs.request_host_tier_saved_tokens().labels(
        model="metrics-probe").observe(512)
    # Session-continuity KV handoff families (ISSUE 19): the drain
    # parachute's export outcomes, re-attach adoption outcomes, the
    # peer-transfer pull outcomes, and the export wall-time histogram —
    # representative samples so names, label shapes, and unit suffixes
    # always lint.
    for outcome in ("exported", "skipped", "dropped", "failed"):
        obs.kv_handoff_exported_blocks_total().labels(
            model="metrics-probe", outcome=outcome).inc()
    for outcome in ("adopted", "duplicate", "corrupt", "truncated",
                    "torn", "version_skew", "dropped_capacity",
                    "failed"):
        obs.kv_handoff_reattached_blocks_total().labels(
            model="metrics-probe", outcome=outcome).inc()
    for outcome in ("imported", "digest_mismatch", "skipped",
                    "failed"):
        obs.kv_handoff_peer_blocks_total().labels(
            model="metrics-probe", outcome=outcome).inc()
    obs.kv_handoff_export_ms().labels(
        model="metrics-probe").observe(14.0)
    # Model residency & affinity routing families (ISSUE 15): the
    # residency state/fault-in telemetry, the admission-aware
    # eviction-skip counter, and the router's affinity-pick outcomes —
    # representative samples so names, label shapes, and unit suffixes
    # always lint.
    obs.residency_state().labels(model="metrics-probe").set(3.0)
    for source, ms in (("warm", 12.0), ("cold", 850.0)):
        obs.residency_fault_in_ms().labels(source=source).observe(ms)
    for outcome in ("warm", "cold", "coalesced", "error"):
        obs.residency_fault_ins_total().labels(
            model="metrics-probe", outcome=outcome).inc()
    obs.hbm_eviction_skips_total().labels(
        model="metrics-probe", reason="busy").inc()
    for mode in ("model", "prefix"):
        for outcome in ("ring", "spill", "fallback"):
            obs.router_affinity_total().labels(
                mode=mode, outcome=outcome).inc()
    # Speculative-decoding families (ISSUE 20): proposal/acceptance
    # counters split by proposer, the chaos-fallback counter split by
    # seam, the accepted-length and draft-overhead histograms, and the
    # bounded acceptance-rate gauge — representative samples so names,
    # label shapes, and unit suffixes always lint.
    for proposer in ("draft", "ngram"):
        obs.specdec_proposed_tokens_total().labels(
            model="metrics-probe", proposer=proposer).inc(12)
        obs.specdec_accepted_tokens_total().labels(
            model="metrics-probe", proposer=proposer).inc(7)
        obs.specdec_draft_ms().labels(
            model="metrics-probe", proposer=proposer).observe(0.4)
    for site in ("draft", "verify"):
        obs.specdec_fallbacks_total().labels(
            model="metrics-probe", site=site).inc()
    obs.specdec_accepted_length_tokens().labels(
        model="metrics-probe").observe(3)
    obs.specdec_acceptance_ratio().labels(
        model="metrics-probe").set(0.58)
    # Device-discipline sanitizer families (ISSUE 14): the violation
    # counter (one sample per kind) and the armed gauge, touched with
    # representative values so names/labels/suffixes always lint.
    for kind in ("forbidden_transfer", "recompile", "loop_stall"):
        obs.sanitizer_violations_total().labels(kind=kind).inc()
    obs.sanitizer_armed().set(1)
    # Telemetry history & trend families (ISSUE 17): the sampler's
    # self-metrics, the synthetic ratio series (bounded [0, 1]), and
    # the trend detector's slope/z-score/change-point exports — one
    # real tick over the populated registries plus representative
    # touches so names, label shapes, and unit suffixes always lint.
    if server.history is not None:
        server.history.tick()
        server.history.tick()
    obs.history_tick_ms().observe(0.8)
    obs.history_tick_failures_total().inc()
    obs.history_samples_total().inc(64)
    obs.history_series().set(17.0)
    obs.trend_slope_per_second().labels(
        series="kfserving_tpu_request_latency_ms_p99",
        model="metrics-probe").set(2.5)
    obs.trend_zscore().labels(
        series="kfserving_tpu_request_latency_ms_p99",
        model="metrics-probe").set(4.2)
    obs.trend_changepoints_total().labels(
        series="kfserving_tpu_request_latency_ms_p99").inc()
    # Incident-engine families (ISSUE 18): the per-key open gauge,
    # the cause-labeled open counter, the kind-labeled trigger
    # counter, the failure counter (every reason the worker can
    # shed), and the duration histogram — touched with
    # representative values so names, label shapes, and unit
    # suffixes always lint.
    obs.incident_open().labels(model="metrics-probe").set(1)
    obs.incident_open().labels(model="_server").set(0)
    for cause in ("queue_wait", "device_compute", "cache_miss_storm",
                  "eviction_thrash", "recompile_host_sync",
                  "brownout_shed", "failover", "unclassified"):
        obs.incident_opened_total().labels(cause=cause).inc()
    for kind in ("slo_breach", "trend", "sanitizer", "eviction_storm",
                 "faultback_storm", "failover"):
        obs.incident_triggers_total().labels(kind=kind).inc()
    for reason in ("error", "dropped", "spool"):
        obs.incident_failures_total().labels(reason=reason).inc()
    obs.incident_duration_ms().observe(42_000.0)
    problems: List[str] = []
    if resp.status != 200:
        problems.append(
            f"smoke request failed with status {resp.status}")
    problems += lint_exposition(server.metrics.render())
    problems += lint_families(server.metrics.registry.families())
    problems += lint_families(REGISTRY.families())
    # Deduplicate: a family can be flagged by both the exposition and
    # the registry pass.
    return sorted(set(problems))


def main() -> int:
    problems = asyncio.run(smoke())
    if problems:
        print("metrics lint FAILED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print("metrics lint OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
