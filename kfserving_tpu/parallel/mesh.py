"""Device mesh construction for serving replicas.

A serving replica owns some set of ICI-connected chips (v5e-1, v5e-4,
v5e-8...).  The mesh axes follow the scaling-book convention:

- ``dp``: data parallel — request batches split across this axis; no
  parameter communication.
- ``tp``: tensor parallel — transformer weight matrices shard across this
  axis; activations all-reduce over ICI inside each layer.
- ``sp``: sequence parallel — long-context attention rotates K/V around
  this axis (ring attention).

Axis sizes are static per-deployment config (the control-plane spec's
`parallelism` block, control/spec.py); there is no dynamic re-meshing — a
new mesh is a new model load, same as a replica restart in the reference.
"""

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape.  Sizes of 1 are valid (axis present but
    trivial) so jitted code can always reference all three axes."""

    dp: int = 1
    tp: int = 1
    sp: int = 1
    axis_order: Sequence[str] = ("dp", "sp", "tp")

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.sp

    def sizes(self) -> Dict[str, int]:
        return {"dp": self.dp, "tp": self.tp, "sp": self.sp}


def build_mesh(config: Optional[MeshConfig] = None, devices=None,
               **axis_sizes):
    """Build a jax.sharding.Mesh from a MeshConfig (or dp=/tp=/sp= kwargs).

    Axis order puts ``tp`` innermost: tensor-parallel collectives are the
    most latency-sensitive, and innermost mesh axes map to the
    closest-neighbor ICI links on TPU device orderings.
    """
    import jax
    from jax.sharding import Mesh

    if config is None:
        config = MeshConfig(**axis_sizes)
    devices = list(devices if devices is not None else jax.devices())
    n = config.num_devices
    if n > len(devices):
        raise ValueError(
            f"mesh {config.sizes()} needs {n} devices; "
            f"{len(devices)} available")
    shape = tuple(getattr(config, a) for a in config.axis_order)
    dev_array = np.array(devices[:n]).reshape(shape)
    return Mesh(dev_array, tuple(config.axis_order))


def single_device_mesh(device=None):
    """Degenerate 1-device mesh so single-chip and multi-chip serving share
    one code path (everything is pjit over a mesh; XLA elides the trivial
    collectives)."""
    import jax

    return build_mesh(MeshConfig(), devices=[device or jax.devices()[0]])
