"""Multi-host distributed runtime: DCN x ICI hybrid meshes.

The reference's "distributed backend" is mesh networking — replicas are
HTTP peers and the control plane signals through ConfigMaps (SURVEY.md
§5.8); there is no NCCL/MPI anywhere.  The TPU build keeps that shape
for replica-to-replica traffic and adds what the reference couldn't
have: a single *model* spanning multiple hosts, with XLA collectives
riding ICI within a slice and DCN between slices.

Two pieces:

- ``initialize()``: one-call `jax.distributed` bring-up.  Every host in
  the slice (or multi-slice job) runs the same binary; coordinates come
  from arguments or the standard env (COORDINATOR_ADDRESS / NUM_PROCESSES
  / PROCESS_ID), and on Cloud TPU metadata auto-detection means no args
  at all.  Idempotent — safe to call from every entrypoint.

- ``hybrid_mesh()``: a mesh whose outermost axis ("dcn") spans slices
  and whose inner axes (dp/sp/tp) span the ICI within each slice, via
  jax.experimental.mesh_utils.create_hybrid_device_mesh.  Sharding
  rules stay written against dp/sp/tp; batches additionally split over
  "dcn" (pure data parallelism between slices — the only traffic that
  should cross DCN per the scaling-book recipe: keep collectives on
  ICI, gradients/batches on DCN).
"""

import logging
import os
from typing import Optional

from kfserving_tpu.parallel.mesh import MeshConfig

logger = logging.getLogger("kfserving_tpu.parallel.multihost")

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Bring up jax.distributed across hosts; returns True when running
    distributed, False when single-process (no coordinates anywhere).

    Priority: explicit args > COORDINATOR_ADDRESS/NUM_PROCESSES/
    PROCESS_ID env > Cloud TPU metadata autodetection (args all None).
    Single-host serving never needs this — the call is a no-op without
    coordinates.
    """
    global _initialized
    import jax

    if _initialized:
        return jax.process_count() > 1
    try:
        # Already brought up externally (an entrypoint called
        # jax.distributed.initialize directly): adopt it instead of a
        # second initialize, which raises once the backend exists.
        from jax._src.distributed import global_state

        adopted = getattr(global_state, "coordinator_address", None)
        if adopted:
            _initialized = True
            if num_processes is not None and \
                    num_processes != jax.process_count():
                logger.warning(
                    "adopting an externally-initialized distributed "
                    "runtime with %d processes, but the caller asked "
                    "for %d — topology mismatch",
                    jax.process_count(), num_processes)
            if coordinator_address is not None and \
                    coordinator_address != adopted:
                logger.warning(
                    "adopting an externally-initialized distributed "
                    "runtime at %s, but the caller asked for "
                    "coordinator %s — possible wrong-cluster adoption",
                    adopted, coordinator_address)
            return jax.process_count() > 1
    except ImportError:  # pragma: no cover - private API moved
        pass
    coordinator_address = coordinator_address or os.getenv(
        "COORDINATOR_ADDRESS")
    if num_processes is None and os.getenv("NUM_PROCESSES"):
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and os.getenv("PROCESS_ID"):
        process_id = int(os.environ["PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        tpu_env = os.getenv("TPU_WORKER_HOSTNAMES")
        if not tpu_env:
            logger.info("no distributed coordinates; single-process mode")
            return False
        # Cloud TPU: jax.distributed autodetects from metadata.
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    _initialized = True
    logger.info("distributed runtime up: process %d/%d, %d local + %d "
                "global devices", jax.process_index(),
                jax.process_count(), jax.local_device_count(),
                jax.device_count())
    return jax.process_count() > 1


def hybrid_mesh(config: Optional[MeshConfig] = None,
                dcn_replicas: int = 1, devices=None, **axis_sizes):
    """Mesh with axes ("dcn", dp, sp, tp): "dcn" spans slices (data
    parallel over the data-center network), the rest span ICI.

    With dcn_replicas=1 this degenerates to a 4-axis single-slice mesh,
    so jitted code always references the same axis names whether the
    deployment is one chip, one slice, or a multi-slice fleet.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if config is None:
        config = MeshConfig(**axis_sizes)
    devices = list(devices if devices is not None else jax.devices())
    per_slice = config.num_devices
    need = per_slice * dcn_replicas
    if need > len(devices):
        raise ValueError(
            f"hybrid mesh needs {need} devices "
            f"({config.sizes()} x dcn={dcn_replicas}); "
            f"{len(devices)} available")
    ici_shape = tuple(getattr(config, a) for a in config.axis_order)
    axis_names = ("dcn",) + tuple(config.axis_order)
    if dcn_replicas > 1 and jax.process_count() > 1:
        from jax.experimental import mesh_utils

        # The DCN granule: TPU multi-slice devices carry distinct
        # slice_index values and group by slice; hosts whose devices
        # don't (CPU fleets, single-slice-per-host jobs) group by
        # process — the process boundary IS the DCN boundary there.
        slice_ids = {getattr(d, "slice_index", None)
                     for d in devices[:need]}
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, (dcn_replicas,) + (1,) * len(ici_shape),
            devices=devices[:need],
            process_is_granule=len(slice_ids) <= 1)
        # create_hybrid_device_mesh returns shape dcn*ici flattened per
        # axis; reshape to (dcn, *ici).
        dev_array = dev_array.reshape((dcn_replicas,) + ici_shape)
    else:
        dev_array = np.array(devices[:need]).reshape(
            (dcn_replicas,) + ici_shape)
    return Mesh(dev_array, axis_names)


def data_sharding(mesh):
    """Batch sharding for a hybrid mesh: leading batch dim splits over
    (dcn, dp) — between-slice data parallelism costs zero collectives in
    the forward pass."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(("dcn", "dp")))
