"""Parameter and activation sharding rules.

Megatron-style tensor parallelism for the transformer zoo, expressed as
regex → PartitionSpec rules over flattened Flax param paths:

- q/k/v projections shard the *heads* (output) dimension on ``tp``: each
  device computes its own heads, no communication.
- attention output and MLP down projections shard the *input* dimension on
  ``tp``: XLA inserts the single per-layer psum over ICI.
- embeddings/layernorms/heads replicate (serving batch sizes keep them
  cheap; vocab-sharded embeddings only pay off at training scale).

`shard_params` applies the first matching rule per leaf and `device_put`s
with a NamedSharding, so the engine's jitted apply becomes an SPMD program
with XLA-chosen collectives — the TPU-native replacement for the NCCL/MPI
backends the reference never had (SURVEY.md §5.8).
"""

import re
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def transformer_rules() -> Sequence[Tuple[str, P]]:
    """Rules matched against '/'-joined param paths, first match wins.
    Covers models/bert.py and models/vit.py module names."""
    return (
        # Attention projections: DenseGeneral kernels [hidden, heads, dim]
        (r".*(query|key|value)/kernel$", P(None, "tp", None)),
        (r".*(query|key|value)/bias$", P("tp", None)),
        # Attention out-proj: [heads, dim, hidden] — contract dims sharded
        (r".*attention.*/out/kernel$|.*/out/kernel$", P("tp", None, None)),
        # MLP up: [hidden, intermediate]
        (r".*(intermediate|mlp_in)/kernel$", P(None, "tp")),
        (r".*(intermediate|mlp_in)/bias$", P("tp")),
        # MLP down: [intermediate, hidden]
        (r".*(output|mlp_out)/kernel$", P("tp", None)),
        # Everything else (embeddings, norms, heads, convs): replicated
        (r".*", P()),
    )


def _leaf_spec(path: str, shape: Tuple[int, ...],
               rules: Sequence[Tuple[str, P]],
               mesh: Optional[Mesh] = None) -> P:
    for pattern, spec in rules:
        if re.match(pattern, path):
            # Guard: a spec longer than the leaf's rank means the rule was
            # written for a different layer shape — replicate instead of
            # failing placement.
            if len(spec) > len(shape):
                return P()
            if mesh is not None:
                # Drop mesh axes that don't divide the dimension (e.g. 4
                # heads over tp=3): replicate that dim instead of failing.
                cleaned = []
                for dim, axis in zip(shape, spec):
                    size = mesh.shape.get(axis, 1) if axis else 1
                    cleaned.append(axis if dim % size == 0 else None)
                return P(*cleaned)
            return spec
    return P()


def _flatten_with_paths(tree: Any):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = []
    for keypath, leaf in flat:
        parts = []
        for k in keypath:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        paths.append(("/".join(parts), leaf))
    return paths, treedef


def param_specs(params: Any,
                rules: Optional[Sequence[Tuple[str, P]]] = None,
                mesh: Optional[Mesh] = None) -> Any:
    """PartitionSpec pytree matching `params` (for pjit in_shardings).
    With `mesh`, specs are validated against leaf shapes (non-dividing axes
    replicate)."""
    rules = rules if rules is not None else transformer_rules()
    flat, treedef = _flatten_with_paths(params)
    specs = [_leaf_spec(path, getattr(leaf, "shape", ()), rules, mesh)
             for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_params(params: Any, mesh: Mesh,
                 rules: Optional[Sequence[Tuple[str, P]]] = None) -> Any:
    """Place a param pytree onto the mesh per the rules."""
    specs = param_specs(params, rules, mesh=mesh)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, specs)


def replicate_params(params: Any, mesh: Mesh) -> Any:
    """Fully replicate (dp-only serving; ResNet/MLP zoo)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding), params)


def batch_sharding(mesh: Mesh, batch_axis: str = "dp") -> NamedSharding:
    """Input batches split along dp; all other dims replicated."""
    return NamedSharding(mesh, P(batch_axis))


def shard_batch(batch: Any, mesh: Mesh,
                batch_axis: str = "dp") -> Any:
    sharding = batch_sharding(mesh, batch_axis)
    return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding), batch)


def describe(params: Any,
             rules: Optional[Sequence[Tuple[str, P]]] = None
             ) -> Dict[str, str]:
    """path -> spec string, for debugging/ops visibility."""
    rules = rules if rules is not None else transformer_rules()
    flat, _ = _flatten_with_paths(params)
    return {path: str(_leaf_spec(path, getattr(leaf, "shape", ()), rules))
            for path, leaf in flat}
