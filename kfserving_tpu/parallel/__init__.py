"""Multi-chip parallelism: device meshes, sharding rules, ring attention.

The reference scales only at replica granularity (Knative KPA over
`minReplicas/maxReplicas`, reference
pkg/controller/v1beta1/inferenceservice/reconcilers/knative/
ksvc_reconciler.go:70-83) and never touches model internals — SURVEY.md §2.3
and §5.7 audit this.  The TPU-native build adds the within-replica dimension
the reference could not have: a replica is an ICI-connected device mesh, and
one served model is an SPMD program over it.

- mesh.py:     mesh construction over dp/tp/sp axes (ICI within a replica,
               DCN between replicas — replicas stay plain HTTP peers exactly
               like the reference's).
- sharding.py: parameter/activation PartitionSpec rules for the model zoo
               (Megatron-style tensor parallelism for transformer blocks)
               and `shard_params` placement helpers.
- ring_attention.py: sequence-parallel attention via `shard_map` +
               `ppermute` — K/V blocks rotate around the ring while each
               device keeps an online-softmax accumulator, so attention over
               sequences longer than one chip's HBM rides ICI.
"""

from kfserving_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    single_device_mesh,
)
from kfserving_tpu.parallel.multihost import (  # noqa: F401
    hybrid_mesh,
    initialize as initialize_distributed,
)
from kfserving_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    replicate_params,
    shard_params,
    transformer_rules,
)
