"""Ring attention: sequence-parallel attention over an ICI ring.

For contexts too long for one chip's HBM, the sequence axis shards over the
mesh's ``sp`` axis.  Each device holds its local Q/K/V block; K/V blocks
rotate around the ring with `lax.ppermute` while every device folds each
visiting block into a running online-softmax accumulator (same math as the
Pallas flash kernel, lifted to the mesh level).  After sp steps every query
has attended to the full sequence; communication overlaps compute because
each ppermute is issued before the block is consumed.

No reference counterpart exists (SURVEY.md §5.7 audits its absence); this is
the long-context requirement built TPU-first: collectives ride ICI, the
sequence never materializes on one device, and the whole thing jits inside
the engine's pjit program.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, kv_mask, axis_name: str, causal: bool):
    """Per-device body under shard_map.

    q, k, v: [B, L_local, H, D] local sequence blocks.
    kv_mask: [B, L_local] bool (True = real token) — rotates around the
        ring alongside its K/V block so padding never attends.
    The sp axis index orders blocks: device i holds positions
    [i*L_local, (i+1)*L_local).
    """
    # jax.lax.axis_size arrived after 0.4.x; psum of a literal 1 is
    # the historical spelling and is constant-folded to the same
    # static axis size, so either works as a loop bound.
    sp = (jax.lax.axis_size(axis_name)
          if hasattr(jax.lax, "axis_size")
          else jax.lax.psum(1, axis_name))
    my_idx = jax.lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    scale = 1.0 / D ** 0.5
    qf = q.astype(jnp.float32) * scale

    def fold(carry, kv_block, block_idx):
        acc, m_prev, l_prev = carry
        kf, vf, mask_blk = kv_block
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf.astype(jnp.float32))
        Lk = kf.shape[1]
        if causal:
            q_pos = (my_idx * Lq
                     + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0))
            k_pos = (block_idx * Lk
                     + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1))
            s = jnp.where((q_pos >= k_pos)[None, None], s, _NEG_INF)
        # [B, Lk] -> [B, 1, 1, Lk]: mask padded keys in this block.
        s = jnp.where(mask_blk[:, None, None, :], s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)          # [B,H,Lq,1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vf.astype(jnp.float32))
        acc_new = acc * alpha.transpose(0, 2, 1, 3) + pv
        return acc_new, m_new, l_new

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(i, state):
        k_blk, v_blk, m_blk, acc, m, l = state
        # Block owner index walks backwards around the ring from my_idx.
        block_idx = (my_idx - i) % sp
        acc, m, l = fold((acc, m, l), (k_blk, v_blk, m_blk), block_idx)
        # Rotate for the next step (skipped result on the last iteration —
        # lax.fori_loop still issues it; cheap relative to the folds and
        # keeps the loop body uniform).
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        m_blk = jax.lax.ppermute(m_blk, axis_name, perm)
        return k_blk, v_blk, m_blk, acc, m, l

    acc0 = jnp.zeros((B, Lq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Lq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lq, 1), jnp.float32)
    _, _, _, acc, m, l = jax.lax.fori_loop(
        0, sp, step, (k, v, kv_mask, acc0, m0, l0))
    # Fully-masked query rows (padding) would divide by zero; clamp — their
    # outputs are sliced off / ignored downstream anyway.
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Mesh, axis_name: str = "sp",
                   causal: bool = False,
                   kv_mask: Optional[jax.Array] = None,
                   batch_axis: Optional[str] = "dp") -> jax.Array:
    """Sequence-parallel attention over [B, L, H, D] with L sharded on
    `axis_name` (and optionally B on `batch_axis`).

    kv_mask: optional [B, L] bool/int padding mask (True = attend to that
    key position); it shards and rotates with the K/V blocks.

    Call inside or outside jit; inputs need not be pre-sharded (shard_map
    constraints will move them), but pre-sharded inputs avoid the reshard.
    """
    if q.shape[1] % mesh.shape[axis_name]:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name}={mesh.shape[axis_name]}")
    # Batch sharding is best-effort: module init traces with batch=1, which
    # can't split over dp — replicate batch in that case, shard otherwise.
    if batch_axis is not None and q.shape[0] % mesh.shape[batch_axis]:
        batch_axis = None
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], jnp.bool_)
    else:
        kv_mask = kv_mask.astype(jnp.bool_)
    spec = P(batch_axis, axis_name, None, None)
    mask_spec = P(batch_axis, axis_name)
    fn = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal)
    try:
        from jax import shard_map

        sharded = shard_map(fn, mesh=mesh,
                            in_specs=(spec, spec, spec, mask_spec),
                            out_specs=spec, check_vma=False)
    except (ImportError, TypeError):  # older jax spells it differently
        from jax.experimental.shard_map import shard_map as shard_map_old

        sharded = shard_map_old(fn, mesh=mesh,
                                in_specs=(spec, spec, spec, mask_spec),
                                out_specs=spec, check_rep=False)
    return sharded(q, k, v, kv_mask)


def ring_attention_sharded(mesh: Mesh, axis_name: str = "sp",
                           batch_axis: Optional[str] = "dp",
                           causal: bool = False):
    """Returns a jit-ready closure over the mesh in the model zoo's
    pluggable-attention calling convention (q, k, v, mask) where mask is a
    broadcastable [B, 1, 1, L] or [B, L] key-padding mask."""
    def attn(q, k, v, mask=None):
        if mask is not None and mask.ndim == 4:
            # [B, 1, 1, L] (BERT-style broadcast mask) -> [B, L]
            mask = mask[:, 0, 0, :]
        return ring_attention(q, k, v, mesh, axis_name=axis_name,
                              causal=causal, kv_mask=mask,
                              batch_axis=batch_axis)
    return attn
