"""Labeled metrics registry with OpenMetrics exemplars.

The seed's `server/metrics.py` hand-rolled one counter, one histogram,
and a string-keyed gauge map; every new subsystem (batcher, engine,
generator, reliability) needed its own ad-hoc export path.  This
registry is the shared upgrade: named families of labeled counters /
gauges / histograms, safe label escaping, and exemplars on histogram
buckets linking a latency observation to the trace id that produced it.

Render format is the Prometheus text exposition (version 0.0.4); with
``render(exemplars=True)`` histogram bucket lines additionally carry
OpenMetrics exemplar suffixes:

    name_bucket{le="5"} 12 # {trace_id="4bf9..."} 3.2 1700000000.000

Exemplars are legal ONLY under the ``application/openmetrics-text``
content type — endpoints negotiate on the Accept header and default to
the classic exposition without them (the classic parser rejects the
suffix and drops the whole scrape).  Counters and gauges never carry
exemplars — downstream line parsers (the recycling watchdog scrapes
`kfserving_tpu_request_total` with a `rsplit(" ", 1)` float parse)
must keep working on those series.

Thread-safety: the registry lock guards family registration; each
family carries its own lock guarding its children and their sample
mutation — instruments are touched from asyncio handlers, engine
worker threads, and the generator's enqueue/fetch executors, and a
per-family lock keeps hot paths from serializing against unrelated
instruments.

`REGISTRY` is the process-wide default (the per-process series every
layer feeds and every /metrics endpoint appends).  `Registry.reset()`
drops all families — the test-isolation hook the conftest guard uses.
"""

import bisect
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

LATENCY_BUCKETS_MS = [0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                      2500, 5000, 10000]
RATIO_BUCKETS = [0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 1.0]
THROUGHPUT_BUCKETS = [1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500]

_LabelKey = Tuple[Tuple[str, str], ...]


def escape_label_value(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, and
    newline must be escaped or the exposition line is unparseable."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _format_value(v: float) -> str:
    # Counters render integral values without a trailing ".0" so
    # existing parsers (and humans) see "3", not "3.0".
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class Histogram:
    __slots__ = ("buckets", "counts", "total", "sum", "exemplars",
                 "_lock")

    def __init__(self, buckets: List[float], lock: threading.Lock):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0
        self.sum = 0.0
        # bucket index -> (trace_id, observed value, unix seconds);
        # last observation wins (one live exemplar per bucket).
        self.exemplars: Dict[int, Tuple[str, float, float]] = {}
        self._lock = lock

    def observe(self, value: float,
                trace_id: Optional[str] = None) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        # Locked: engine worker threads observe concurrently, and a
        # lost '+= 1' would leave total != sum(counts) — a broken
        # '+Inf == _count' invariant in the exposition.
        with self._lock:
            self.counts[idx] += 1
            self.total += 1
            self.sum += value
            if trace_id:
                self.exemplars[idx] = (trace_id, float(value),
                                       time.time())


class _Family:
    """One named metric of one kind; children keyed by label values."""

    __slots__ = ("kind", "name", "help", "buckets", "_children",
                 "_lock")

    def __init__(self, kind: str, name: str, help_text: str,
                 buckets: Optional[List[float]], lock: threading.Lock):
        self.kind = kind
        self.name = name
        self.help = help_text
        self.buckets = buckets
        self._children: Dict[_LabelKey, object] = {}
        self._lock = lock

    def labels(self, **labels: str):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.kind == "counter":
                    child = Counter(self._lock)
                elif self.kind == "gauge":
                    child = Gauge(self._lock)
                else:
                    child = Histogram(self.buckets, self._lock)
                self._children[key] = child
            return child

    # Unlabeled convenience: family.inc()/set()/observe() act on the
    # empty-label child.
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def observe(self, value: float,
                trace_id: Optional[str] = None) -> None:
        self.labels().observe(value, trace_id=trace_id)

    def samples(self) -> Iterable[Tuple[Dict[str, str], object]]:
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            yield dict(key), child

    def prune(self, **labels: str) -> int:
        """Drop every child whose labels contain all given pairs —
        series hygiene for label values with bounded lifetimes (a
        GC'd revision's per-revision series must not grow /metrics
        and every scan over the family forever).  Returns the number
        of children removed."""
        match = {(k, str(v)) for k, v in labels.items()}
        with self._lock:
            gone = [key for key in self._children
                    if match <= set(key)]
            for key in gone:
                del self._children[key]
            return len(gone)


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, kind: str, name: str, help_text: str,
                buckets: Optional[List[float]] = None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                # Per-FAMILY lock, not the registry's: hot paths (the
                # generator's per-token counters, engine worker
                # threads) must not serialize against every other
                # instrument in the process.
                fam = _Family(kind, name, help_text, buckets,
                              threading.Lock())
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name} already registered as {fam.kind}, "
                    f"not {kind}")
            return fam

    def counter(self, name: str, help_text: str = "") -> _Family:
        return self._family("counter", name, help_text)

    def gauge(self, name: str, help_text: str = "") -> _Family:
        return self._family("gauge", name, help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Optional[List[float]] = None) -> _Family:
        return self._family("histogram", name, help_text,
                            buckets or LATENCY_BUCKETS_MS)

    # -- introspection ---------------------------------------------------
    def family(self, name: str) -> Optional[_Family]:
        """Read-only lookup of an existing family (None when absent).
        The SLO engine and the metrics linter read families without
        registering them — a reader must never create an empty family
        a later writer would then re-kind against."""
        with self._lock:
            return self._families.get(name)

    def families(self) -> Dict[str, str]:
        """{family name: kind} snapshot for exposition linting."""
        with self._lock:
            return {name: fam.kind
                    for name, fam in self._families.items()}

    # -- introspection (test isolation) ---------------------------------
    def sample_names(self) -> List[str]:
        """Names of families that hold at least one child sample."""
        with self._lock:
            return [name for name, fam in self._families.items()
                    if fam._children]

    def reset(self) -> None:
        with self._lock:
            self._families.clear()

    # -- exposition ------------------------------------------------------
    def render(self, exemplars: bool = True) -> str:
        return "\n".join(self.render_lines(exemplars=exemplars)) + "\n"

    def render_lines(self, exemplars: bool = True) -> List[str]:
        """Prometheus text lines.  ``exemplars=True`` adds OpenMetrics
        exemplar suffixes on histogram buckets — legal ONLY under the
        ``application/openmetrics-text`` content type; endpoints must
        pass False when serving the classic text/plain exposition (the
        classic parser rejects the suffix and drops the whole scrape).
        """
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        lines: List[str] = []
        for fam in families:
            samples = list(fam.samples())
            if not samples:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            if fam.kind == "histogram":
                for labels, hist in samples:
                    self._render_histogram(lines, fam.name, labels,
                                           hist, exemplars)
            else:
                for labels, child in samples:
                    lines.append(f"{fam.name}{format_labels(labels)} "
                                 f"{_format_value(child.value)}")
        return lines

    @staticmethod
    def _render_histogram(lines: List[str], name: str,
                          labels: Dict[str, str],
                          hist: Histogram,
                          exemplars: bool = True) -> None:
        with hist._lock:
            counts = list(hist.counts)
            total = hist.total
            total_sum = hist.sum
            exemplar_map = dict(hist.exemplars)
        cumulative = 0
        for idx, (bound, count) in enumerate(zip(hist.buckets,
                                                 counts)):
            cumulative += count
            bucket_labels = dict(labels)
            bucket_labels["le"] = str(bound)
            line = (f"{name}_bucket{format_labels(bucket_labels)} "
                    f"{cumulative}")
            ex = exemplar_map.get(idx) if exemplars else None
            if ex is not None:
                trace_id, value, ts = ex
                line += (f' # {{trace_id="{escape_label_value(trace_id)}"}}'
                         f" {_format_value(value)} {ts:.3f}")
            lines.append(line)
        inf_labels = dict(labels)
        inf_labels["le"] = "+Inf"
        lines.append(f"{name}_bucket{format_labels(inf_labels)} "
                     f"{total}")
        lines.append(f"{name}_sum{format_labels(labels)} "
                     f"{_format_value(total_sum)}")
        lines.append(f"{name}_count{format_labels(labels)} "
                     f"{total}")


# The process-wide default registry: batcher, engine, generator, and
# reliability series all land here; every /metrics endpoint appends it.
REGISTRY = Registry()
