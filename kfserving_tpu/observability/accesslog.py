"""Structured JSON access log: one line per request.

The reference gets access logs from the mesh (queue-proxy / gateway);
the sidecar-free build emits its own.  Each line is a single JSON
object on the `kfserving_tpu.access` logger so operators can route it
(file, stdout, collector) with standard logging config and parse it
without regexes::

    {"component": "server", "trace_id": "4bf9...", "model": "m",
     "verb": "predict", "status": 200, "latency_ms": 12.3,
     "stages": {"decode": 0.1, "infer": 11.9, "encode": 0.2},
     "tokens_in": 17, "tokens_out": 64}

Fields with value None are dropped; emission never raises (a log
failure must not fail the request).
"""

import json
import logging

logger = logging.getLogger("kfserving_tpu.access")


def log_access(component: str, **fields) -> None:
    record = {"component": component}
    record.update((k, v) for k, v in fields.items() if v is not None)
    try:
        logger.info("%s", json.dumps(record, default=str,
                                     sort_keys=True))
    except Exception:  # never let telemetry fail the request
        logger.debug("access log emission failed", exc_info=True)
