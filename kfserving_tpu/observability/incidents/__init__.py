"""Incident engine: automated cross-signal diagnosis.

The join layer over every detector the stack already runs — SLO
burn-rate breaches, trend change-points, sanitizer violations,
eviction/fault-back storms, lifecycle failovers — turning isolated
flight-recorder pins into ONE diagnosed, evidence-bearing incident
record per regression (manager.py), classified against the additive
latency decomposition (classify.py).

Served at replica `GET /debug/incidents`, federated by the router
with fleet-level root-cause dedup, exported as the
`kfserving_tpu_incident_*` registry families, and surfaced through
`client.incidents()` / `kfs incidents` / `kfs doctor`.
"""

from kfserving_tpu.observability.incidents.classify import (
    CAUSES,
    classify,
)
from kfserving_tpu.observability.incidents.manager import (
    IncidentManager,
    incidents_enabled,
)

__all__ = [
    "CAUSES",
    "IncidentManager",
    "classify",
    "incidents_enabled",
]
