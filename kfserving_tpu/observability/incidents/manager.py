"""IncidentManager: detector firings -> diagnosed incident records.

Five telemetry feeds already DETECT regressions independently — SLO
burn-rate breaches, trend change-points, sanitizer violations,
eviction/fault-back storms, lifecycle failovers — each pinning its own
flight-recorder entry.  This manager is the join: every firing becomes
a TRIGGER that either opens an incident or attaches to the open one
for its dedup key (the model under breach, `_server` for process-wide
storms), so one regression produces ONE record instead of five
disconnected pins.

On open the manager snapshots a cross-signal evidence bundle — history
frames for the watched series, the overlapping pinned flight-recorder
entries, the engine-timeline slice for the breach window, top-K
attribution records by device-ms and held blocks, and whatever
snapshot providers the server injected (`/debug/cache` state, router
admission state) — then runs the rule-based causal classifier
(classify.py) over it and stores the ranked hypotheses inline.  The
classifier re-runs on every attach, so accumulating storm triggers
move the ranking while the incident is live.

Never-block discipline (the history sampler's contract): triggers are
a cheap thread-safe enqueue; all diagnosis happens on a background
worker task that probes the `observability.incident_open` fault site
(injected hook) before each event.  An injected error is swallowed
and counted (`kfserving_tpu_incident_failures_total{reason=error}`),
an injected hang parks only the worker while the bounded queue drops
overflow (`reason=dropped`) — the detectors' plain pins keep landing
either way, and predicts never wait on diagnosis.

Close = recovery + cooldown: an incident closes when its SLO alert
has cleared (or never existed) AND no trigger has attached for
`KFS_INCIDENT_COOLDOWN_S`.  Records live in a bounded ring; when
`KFS_INCIDENT_SPOOL_DIR` is set, every open and close also writes
`<id>.json` there THROUGH AN EXECUTOR (no blocking I/O on the loop).

Import discipline (observability package contract): nothing from
`server/`, `control/`, `engine/`, or `reliability/` — the fault hook
and the cache/router snapshot providers are injected at construction.
"""

import asyncio
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from kfserving_tpu.observability import attribution
from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.incidents.classify import classify
from kfserving_tpu.observability.profiling import TIMELINE

logger = logging.getLogger("kfserving_tpu.incidents")

ENV_ENABLED = "KFS_INCIDENTS"
ENV_RING = "KFS_INCIDENT_RING"
ENV_QUEUE = "KFS_INCIDENT_QUEUE"
ENV_COOLDOWN = "KFS_INCIDENT_COOLDOWN_S"
ENV_DEDUP = "KFS_INCIDENT_DEDUP_S"
ENV_WINDOW = "KFS_INCIDENT_WINDOW_S"
ENV_TICK = "KFS_INCIDENT_TICK_S"
ENV_SPOOL = "KFS_INCIDENT_SPOOL_DIR"
ENV_TOPK = "KFS_INCIDENT_TOPK"

DEFAULT_RING = 64
DEFAULT_QUEUE = 256
DEFAULT_COOLDOWN_S = 60.0
DEFAULT_DEDUP_S = 120.0
DEFAULT_WINDOW_S = 120.0
DEFAULT_TICK_S = 0.5
DEFAULT_TOPK = 5
# Per-incident bounds: the record must stay a debug-endpoint payload,
# not a heap leak, no matter how long a storm rains triggers on it.
MAX_TRIGGERS_KEPT = 32
MAX_PINS_IN_BUNDLE = 32
MAX_TIMELINE_EVENTS = 128

# The process-wide dedup key for triggers that have no model (eviction
# storms, sanitizer violations, failovers).
SERVER_KEY = "_server"

# History series the evidence bundle snapshots (pre/post frames for
# each): the request-latency quantiles the SLO breaches on, the
# synthetic health ratios, and the queue-wait quantile the classifier
# separates queue_wait from device_compute with.
EVIDENCE_SERIES = (
    "kfserving_tpu_request_latency_ms_p99",
    "kfserving_tpu_request_latency_ms_p50",
    "kfserving_tpu_history_error_ratio",
    "kfserving_tpu_history_prefix_hit_ratio",
    "kfserving_tpu_batch_queue_wait_ms_p99",
)


def incidents_enabled() -> bool:
    return os.environ.get(ENV_ENABLED, "1") != "0"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class IncidentManager:
    """Bounded incident ring + background diagnosis worker.

    Server-lifecycle service: async `start()`/`stop()` like every
    other entry in `ModelServer.services`."""

    def __init__(self,
                 history=None,
                 recorder=None,
                 providers: Optional[Dict[str, Callable[[], Any]]] = None,
                 fault_hook: Optional[Callable] = None,
                 ring_size: Optional[int] = None,
                 queue_size: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 dedup_window_s: Optional[float] = None,
                 evidence_window_s: Optional[float] = None,
                 tick_s: Optional[float] = None,
                 spool_dir: Optional[str] = None,
                 top_k: Optional[int] = None):
        self.history = history          # HistoryStore or None
        self.recorder = recorder        # FlightRecorder or None
        self.providers = dict(providers or {})
        self.fault_hook = fault_hook
        self.ring_size = max(1, ring_size if ring_size is not None
                             else _env_int(ENV_RING, DEFAULT_RING))
        self.queue_size = max(1, queue_size if queue_size is not None
                              else _env_int(ENV_QUEUE, DEFAULT_QUEUE))
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else _env_float(ENV_COOLDOWN,
                                           DEFAULT_COOLDOWN_S))
        self.dedup_window_s = (dedup_window_s
                               if dedup_window_s is not None
                               else _env_float(ENV_DEDUP,
                                               DEFAULT_DEDUP_S))
        self.evidence_window_s = (evidence_window_s
                                  if evidence_window_s is not None
                                  else _env_float(ENV_WINDOW,
                                                  DEFAULT_WINDOW_S))
        self.tick_s = (tick_s if tick_s is not None
                       else _env_float(ENV_TICK, DEFAULT_TICK_S))
        self.spool_dir = (spool_dir if spool_dir is not None
                          else os.environ.get(ENV_SPOOL) or None)
        self.top_k = max(1, top_k if top_k is not None
                         else _env_int(ENV_TOPK, DEFAULT_TOPK))
        # Trigger queue: appended from the event loop, executor
        # threads, and the sanitizer watchdog alike (deque.append is
        # atomic); drained only by the worker/drain().
        self._queue: deque = deque()
        self._records: deque = deque(maxlen=self.ring_size)
        self._open: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self._task: Optional[asyncio.Task] = None

    # -- service lifecycle -------------------------------------------------
    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(max(0.05, self.tick_s))
            try:
                await self.drain()
            except asyncio.CancelledError:
                raise
            except Exception:
                # The worker itself must survive anything diagnosis
                # throws — drain() already counts per-event failures.
                logger.exception("incident worker tick failed")

    # -- trigger intake (thread-safe, never blocks) ------------------------
    def trigger(self, kind: str, model: Optional[str] = None,
                detail: Optional[Dict[str, Any]] = None,
                ts: Optional[float] = None) -> None:
        """Enqueue one detector firing.  Called synchronously from
        whatever context the detector runs in; all real work happens
        on the worker."""
        try:
            obs.incident_triggers_total().labels(kind=kind).inc()
            if len(self._queue) >= self.queue_size:
                # Bounded: a wedged worker sheds triggers, it never
                # grows the heap.  Detector pins still recorded the
                # evidence — only the JOIN is lost.
                obs.incident_failures_total().labels(
                    reason="dropped").inc()
                return
            self._queue.append({
                "kind": kind,
                "model": model or None,
                "detail": detail or {},
                "ts": time.time() if ts is None else float(ts),
            })
        except Exception:
            logger.exception("incident trigger enqueue failed")

    def on_pin(self, entry: Dict[str, Any]) -> None:
        """Flight-recorder pin tap: map detector pins onto trigger
        kinds.  Request-level pins (latency outliers, single errors)
        are NOT triggers — an incident needs a detector's judgment,
        not one slow request."""
        reason = str(entry.get("pinned") or "")
        ts = entry.get("ts")
        labels = entry.get("labels") or {}
        model = entry.get("model") or labels.get("model")
        if reason.startswith("trend_"):
            self.trigger("trend", model=model, ts=ts, detail={
                "series": entry.get("series"),
                "z": entry.get("z"),
                "value": entry.get("value"),
                "baseline": entry.get("baseline"),
                "slope_per_s": entry.get("slope_per_s")})
        elif reason.startswith("sanitizer_"):
            self.trigger("sanitizer", model=model, ts=ts, detail={
                "kind": reason[len("sanitizer_"):]})
        elif reason == "eviction_storm":
            self.trigger("eviction_storm", model=model, ts=ts,
                         detail={"kind": entry.get("kind")})
        elif reason == "kv_faultback_storm":
            self.trigger("faultback_storm", model=model, ts=ts,
                         detail={"kind": entry.get("kind")})
        elif reason in ("replica_failover", "swap_failure"):
            self.trigger("failover", model=model, ts=ts,
                         detail={"event": reason})

    def on_slo_transition(self, model: str, alerting: bool,
                          burn_rates: Dict[str, Any]) -> None:
        """SLOEngine breach-edge tap (healthy<->alerting)."""
        if alerting:
            self.trigger("slo_breach", model=model,
                         detail={"burn_rates": burn_rates})
        else:
            # Recovery is CLOSE evidence, not a trigger: mark the open
            # incident so the cooldown clock can run out.
            with self._lock:
                incident = self._open.get(model) or \
                    self._open.get(SERVER_KEY)
                if incident is not None:
                    incident["alerting"] = False
                    incident["recovered_ts"] = time.time()

    # -- diagnosis worker --------------------------------------------------
    async def drain(self, now: Optional[float] = None) -> int:
        """Process every queued trigger (fault-site probe per event),
        then run the close sweep.  Returns the number of events
        diagnosed.  Tests drive this directly for determinism; the
        background loop calls it every tick."""
        processed = 0
        while self._queue:
            try:
                event = self._queue.popleft()
            except IndexError:
                break
            try:
                if self.fault_hook is not None:
                    await self.fault_hook()
                self._process(event, now=now)
                processed += 1
            except asyncio.CancelledError:
                raise
            except Exception:
                obs.incident_failures_total().labels(
                    reason="error").inc()
                logger.exception("incident diagnosis failed for %s",
                                 event.get("kind"))
        await self._sweep_closes(now=now)
        return processed

    def _process(self, event: Dict[str, Any],
                 now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        key = event.get("model") or SERVER_KEY
        with self._lock:
            incident = self._open.get(key)
            stale = (incident is not None
                     and not incident.get("alerting")
                     and now - incident["last_trigger_ts"]
                     > self.dedup_window_s)
        if stale:
            # The open incident fell out of the dedup window without a
            # live alert: this firing is a NEW episode, not an attach.
            self._close(incident, now=now)
            incident = None
        if incident is not None:
            self._attach(incident, event, now)
        else:
            self._open_incident(key, event, now)

    def _open_incident(self, key: str, event: Dict[str, Any],
                       now: float) -> None:
        self._seq += 1
        incident_id = f"inc-{self._seq}-{int(now) % 100000}"
        evidence = self._evidence(key, now)
        counts = {event["kind"]: 1}
        hypotheses = classify(counts, evidence)
        cause = hypotheses[0]["cause"] if hypotheses else "unclassified"
        incident = {
            "id": incident_id,
            "state": "open",
            "key": key,
            "model": None if key == SERVER_KEY else key,
            "opened_ts": now,
            "updated_ts": now,
            "last_trigger_ts": now,
            "closed_ts": None,
            # slo_breach opens in the alerting state; everything else
            # only needs the cooldown to run out.
            "alerting": event["kind"] == "slo_breach",
            "recovered_ts": None,
            "triggers": [dict(event)],
            "trigger_counts": counts,
            "evidence": evidence,
            "hypotheses": hypotheses,
            "root_cause": cause,
        }
        with self._lock:
            self._open[key] = incident
            self._records.append(incident)
        obs.incident_open().labels(model=key).set(
            self._open_count(key))
        obs.incident_opened_total().labels(cause=cause).inc()
        logger.warning("incident %s opened (key=%s cause=%s trigger=%s)",
                       incident_id, key, cause, event["kind"])
        self._spool(incident)

    def _attach(self, incident: Dict[str, Any],
                event: Dict[str, Any], now: float) -> None:
        with self._lock:
            incident["updated_ts"] = now
            incident["last_trigger_ts"] = now
            counts = incident["trigger_counts"]
            counts[event["kind"]] = counts.get(event["kind"], 0) + 1
            if len(incident["triggers"]) < MAX_TRIGGERS_KEPT:
                incident["triggers"].append(dict(event))
            if event["kind"] == "slo_breach":
                incident["alerting"] = True
                incident["recovered_ts"] = None
            counts = dict(counts)
            evidence = incident["evidence"]
        # Re-rank outside the lock: classify() is pure over the
        # bundle + updated counts.
        hypotheses = classify(counts, evidence)
        with self._lock:
            incident["hypotheses"] = hypotheses
            if hypotheses:
                incident["root_cause"] = hypotheses[0]["cause"]

    async def _sweep_closes(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        to_close = []
        with self._lock:
            for incident in self._open.values():
                if incident.get("alerting"):
                    continue
                if now - incident["last_trigger_ts"] >= self.cooldown_s:
                    to_close.append(incident)
        for incident in to_close:
            self._close(incident, now=now)

    def _close(self, incident: Dict[str, Any], now: float) -> None:
        with self._lock:
            if incident.get("state") != "open":
                return
            incident["state"] = "closed"
            incident["closed_ts"] = now
            key = incident["key"]
            if self._open.get(key) is incident:
                del self._open[key]
        duration_ms = max(0.0, (now - incident["opened_ts"]) * 1000.0)
        obs.incident_open().labels(model=key).set(
            self._open_count(key))
        obs.incident_duration_ms().observe(duration_ms)
        logger.info("incident %s closed after %.1fs (cause=%s)",
                    incident["id"], duration_ms / 1000.0,
                    incident["root_cause"])
        self._spool(incident)

    def _open_count(self, key: str) -> int:
        with self._lock:
            return 1 if key in self._open else 0

    # -- evidence bundle ---------------------------------------------------
    def _evidence(self, key: str, now: float) -> Dict[str, Any]:
        """Snapshot the cross-signal bundle for the breach window
        [now - evidence_window_s, now].  Every source is best-effort:
        a missing feed yields an absent key, never a failed open."""
        window = self.evidence_window_s
        t0 = now - window
        bundle: Dict[str, Any] = {
            "window": {"start": round(t0, 3), "end": round(now, 3),
                       "span_s": window},
        }
        sources: List[str] = []
        if self.history is not None:
            try:
                series = []
                for name in EVIDENCE_SERIES:
                    series.extend(self.history.query(
                        series=name, window_s=window, now=now))
                bundle["history"] = series
                if series:
                    sources.append("history")
            except Exception:
                logger.exception("history evidence failed")
        if self.recorder is not None:
            try:
                dump = self.recorder.dump(
                    limit=MAX_PINS_IN_BUNDLE, pinned_only=True,
                    since_ts=t0)
                bundle["flightrecorder"] = {
                    "pinned_total": dump.get("pinned_total", 0),
                    "pinned": dump.get("pinned", [])}
                if dump.get("pinned"):
                    sources.append("flightrecorder")
            except Exception:
                logger.exception("flight-recorder evidence failed")
        try:
            events = TIMELINE.window(t0, now,
                                     limit=MAX_TIMELINE_EVENTS)
            bundle["timeline"] = events
            if events:
                sources.append("timeline")
        except Exception:
            logger.exception("timeline evidence failed")
        try:
            by_cost = attribution.top(self.top_k, window_s=window,
                                      by="device_ms", now=now)
            by_blocks = attribution.top(self.top_k, window_s=window,
                                        by="held_blocks", now=now)
            bundle["attribution"] = {
                "top_by_device_ms": by_cost,
                "top_by_held_blocks": by_blocks}
            if by_cost or by_blocks:
                sources.append("attribution")
        except Exception:
            logger.exception("attribution evidence failed")
        for name, provider in self.providers.items():
            try:
                snapshot = provider()
                if snapshot is not None:
                    bundle[name] = snapshot
                    sources.append(name)
            except Exception:
                logger.exception("evidence provider %s failed", name)
        bundle["consistency"] = self._consistency(bundle)
        bundle["sources"] = sources
        return bundle

    @staticmethod
    def _consistency(bundle: Dict[str, Any]) -> Dict[str, Any]:
        """The additive-decomposition cross-check: attributed
        device-ms (per-request records, window-filtered) against the
        engine timeline's device-track busy time for the same window.
        PR 10's discipline says these sum to the same total; an
        incident bundle where they disagree by more than the in-flight
        edge effects is itself a finding."""
        attr_ms = 0.0
        for record in (bundle.get("attribution") or {}).get(
                "top_by_device_ms") or []:
            attr_ms += float(record.get("total_device_ms") or 0.0)
        timeline_ms = 0.0
        for event in bundle.get("timeline") or []:
            if event.get("track") == "device":
                timeline_ms += float(event.get("dur_ms") or 0.0)
        out = {"attribution_device_ms": round(attr_ms, 3),
               "timeline_device_ms": round(timeline_ms, 3)}
        if timeline_ms > 0:
            out["delta_ratio"] = round(
                abs(attr_ms - timeline_ms) / timeline_ms, 4)
        return out

    # -- JSON spool (executor — no blocking I/O on the loop) --------------
    def _spool(self, incident: Dict[str, Any]) -> None:
        if not self.spool_dir:
            return
        snapshot = self.get(incident["id"])
        if snapshot is None:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            loop.run_in_executor(None, self._spool_write, snapshot)
        else:
            # No loop (unit tests driving the manager synchronously):
            # a short-lived thread keeps the invariant that the spool
            # NEVER writes on the calling thread.
            threading.Thread(target=self._spool_write,
                             args=(snapshot,), daemon=True).start()

    def _spool_write(self, snapshot: Dict[str, Any]) -> None:
        try:
            os.makedirs(self.spool_dir, exist_ok=True)
            path = os.path.join(self.spool_dir,
                                f"{snapshot['id']}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snapshot, f)
            os.replace(tmp, path)
        except Exception:
            obs.incident_failures_total().labels(reason="spool").inc()
            logger.exception("incident spool write failed")

    # -- query surface -----------------------------------------------------
    def get(self, incident_id: str) -> Optional[Dict[str, Any]]:
        """Full record (evidence bundle included) by id."""
        with self._lock:
            for incident in self._records:
                if incident["id"] == incident_id:
                    # JSON round-trip = deep copy + serializability
                    # guarantee in one move (default=str mops up any
                    # non-JSON value a provider snuck into evidence).
                    return json.loads(json.dumps(incident,
                                                 default=str))
        return None

    def list(self, state: Optional[str] = None,
             limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first incident summaries (no evidence payload —
        fetch the detail by id)."""
        limit = max(0, int(limit))
        with self._lock:
            records = list(self._records)
        records.reverse()
        out = []
        for incident in records:
            if state and incident["state"] != state:
                continue
            top = (incident["hypotheses"][0]
                   if incident["hypotheses"] else None)
            out.append({
                "id": incident["id"],
                "state": incident["state"],
                "model": incident["model"],
                "opened_ts": incident["opened_ts"],
                "updated_ts": incident["updated_ts"],
                "closed_ts": incident["closed_ts"],
                "root_cause": incident["root_cause"],
                "top_hypothesis": top,
                "trigger_counts": dict(incident["trigger_counts"]),
                "evidence_sources": list(
                    incident["evidence"].get("sources") or []),
            })
            if len(out) >= limit:
                break
        return out

    def report(self, state: Optional[str] = None,
               limit: int = 50) -> Dict[str, Any]:
        """The GET /debug/incidents list body."""
        with self._lock:
            open_count = len(self._open)
            total = self._seq
        return {
            "enabled": True,
            "open": open_count,
            "total_opened": total,
            "queued_triggers": len(self._queue),
            "incidents": self.list(state=state, limit=limit),
        }
