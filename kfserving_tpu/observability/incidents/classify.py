"""Rule-based causal classifier over the additive decomposition.

InferLine's premise (arXiv:1812.01776) is that a serving pipeline's
latency decomposes additively — queue wait + host work + device
compute — and that the decomposition, not the end-to-end number, is
what diagnosis needs.  This module turns an incident's cross-signal
evidence bundle into a RANKED list of causal hypotheses, each scored
0..1 from the numbers the bundle already holds, with those numbers
repeated inline so an operator (or `kfs doctor`) never has to re-join
the telemetry by hand.

The taxonomy (one rule per cause):

    queue_wait          latency is dominated by time spent waiting
                        for an admission slot / batch flush, not work
    device_compute      the infer stage (engine dispatches) dominates
                        — the chip itself got slower or the work grew
    cache_miss_storm    the prefix-cache hit ratio collapsed, so
                        prefill compute that was saved is back
    eviction_thrash     the block pool / residency / host KV tier is
                        churning state faster than requests finish
    recompile_host_sync the sanitizer caught recompiles or implicit
                        host<->device transfers on the hot path
    brownout_shed       requests are being shed by admission control,
                        not served slowly
    failover            replica death / swap failure — capacity, not
                        performance

Scores are heuristic but DETERMINISTIC: the same bundle always ranks
the same way, which is what the e2e tests pin down.  Every hypothesis
carries an `evidence` dict of the exact numbers its score came from.

Import discipline: pure functions over plain dicts — nothing outside
the standard library.
"""

from typing import Any, Dict, List, Optional, Tuple

# Pin reasons that represent one slow/failed REQUEST (as opposed to a
# detector firing) — the per-request additive decomposition lives in
# these entries' `stages` dicts.
REQUEST_PINS = ("slo_breach", "slo_violation", "latency_outlier",
                "deadline_shed", "error")

CAUSES = ("queue_wait", "device_compute", "cache_miss_storm",
          "eviction_thrash", "recompile_host_sync", "brownout_shed",
          "failover")

PREFIX_HIT_SERIES = "kfserving_tpu_history_prefix_hit_ratio"
QUEUE_WAIT_SERIES = "kfserving_tpu_batch_queue_wait_ms_p99"
LATENCY_P99_SERIES = "kfserving_tpu_request_latency_ms_p99"


def _clamp01(value: float) -> float:
    return min(1.0, max(0.0, float(value)))


def _series_frames(evidence: Dict[str, Any],
                   name: str) -> List[List[float]]:
    """All frames for `name` across label sets, concatenated in time
    order (diagnosis wants the shape, not the per-label split)."""
    frames: List[List[float]] = []
    for series in evidence.get("history") or []:
        if series.get("name") == name:
            frames.extend(series.get("frames") or [])
    frames.sort(key=lambda f: f[0])
    return frames


def _pre_post_means(frames: List[List[float]]
                    ) -> Tuple[Optional[float], Optional[float]]:
    """Mean of the first and second half of a frame list — the
    cheapest possible "did this series move across the window"."""
    if len(frames) < 4:
        return None, None
    mid = len(frames) // 2
    pre = [f[1] for f in frames[:mid]]
    post = [f[1] for f in frames[mid:]]
    return sum(pre) / len(pre), sum(post) / len(post)


def _request_pins(evidence: Dict[str, Any]) -> List[Dict[str, Any]]:
    pins = (evidence.get("flightrecorder") or {}).get("pinned") or []
    return [e for e in pins
            if e.get("pinned") in REQUEST_PINS
            and isinstance(e.get("latency_ms"), (int, float))
            and float(e["latency_ms"]) > 0]


def _stage_shares(evidence: Dict[str, Any]
                  ) -> Tuple[Optional[float], Optional[float], int]:
    """(mean infer-stage share, mean unattributed-wait share, n) over
    the bundle's pinned slow requests.  The unattributed wait —
    latency minus every recorded stage — is admission-queue time plus
    loop overhead: the queue-wait component of the decomposition as
    seen per request."""
    infer_shares: List[float] = []
    wait_shares: List[float] = []
    for entry in _request_pins(evidence):
        latency = float(entry["latency_ms"])
        stages = entry.get("stages") or {}
        if not stages:
            continue
        staged = sum(float(v) for v in stages.values()
                     if isinstance(v, (int, float)))
        infer = float(stages.get("infer") or 0.0)
        infer_shares.append(_clamp01(infer / latency))
        wait_shares.append(_clamp01((latency - staged) / latency))
    if not infer_shares:
        return None, None, 0
    n = len(infer_shares)
    return (sum(infer_shares) / n, sum(wait_shares) / n, n)


def classify(trigger_counts: Dict[str, int],
             evidence: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Rank the causal hypotheses for one incident.  Returns a list of
    `{"cause", "score", "summary", "evidence"}` dicts sorted by score
    descending; zero-scored causes are dropped.  An empty list means
    the bundle held no usable decomposition — callers report the
    incident as `unclassified` rather than guessing."""
    hypotheses: List[Dict[str, Any]] = []
    infer_share, wait_share, n_pins = _stage_shares(evidence)
    lat_frames = _series_frames(evidence, LATENCY_P99_SERIES)
    lat_latest = lat_frames[-1][1] if lat_frames else None

    # -- queue_wait ------------------------------------------------------
    queue_frames = _series_frames(evidence, QUEUE_WAIT_SERIES)
    queue_p99 = queue_frames[-1][1] if queue_frames else None
    queue_score = 0.0
    queue_ev: Dict[str, Any] = {}
    if wait_share is not None:
        queue_score = wait_share
        queue_ev["unattributed_wait_share"] = round(wait_share, 4)
        queue_ev["pinned_requests"] = n_pins
    if queue_p99 is not None and lat_latest:
        hist_share = _clamp01(queue_p99 / lat_latest)
        queue_score = max(queue_score, hist_share)
        queue_ev["batch_queue_wait_ms_p99"] = round(queue_p99, 3)
        queue_ev["request_latency_ms_p99"] = round(lat_latest, 3)
    if queue_score > 0:
        hypotheses.append({
            "cause": "queue_wait",
            "score": round(queue_score, 4),
            "summary": ("requests spend "
                        f"{queue_score:.0%} of their latency waiting, "
                        "not computing"),
            "evidence": queue_ev})

    # -- device_compute --------------------------------------------------
    if infer_share is not None:
        # The infer stage dominating WHILE the queue does not is the
        # device-compute signature; a saturated queue re-explains a
        # high infer share (everything is slow), so it discounts.
        device_score = infer_share * (1.0 - _clamp01(wait_share or 0.0))
        device_ev: Dict[str, Any] = {
            "infer_stage_share": round(infer_share, 4),
            "pinned_requests": n_pins}
        consistency = evidence.get("consistency") or {}
        for key in ("attribution_device_ms", "timeline_device_ms",
                    "delta_ratio"):
            if key in consistency:
                device_ev[key] = consistency[key]
        if lat_latest is not None:
            device_ev["request_latency_ms_p99"] = round(lat_latest, 3)
        if device_score > 0:
            hypotheses.append({
                "cause": "device_compute",
                "score": round(device_score, 4),
                "summary": (f"the infer stage is {infer_share:.0%} of "
                            "pinned request latency — the compute "
                            "itself got slower"),
                "evidence": device_ev})

    # -- cache_miss_storm ------------------------------------------------
    hit_frames = _series_frames(evidence, PREFIX_HIT_SERIES)
    pre_hit, post_hit = _pre_post_means(hit_frames)
    if pre_hit is not None and pre_hit >= 0.2:
        drop = max(0.0, pre_hit - post_hit)
        miss_score = _clamp01(2.0 * drop)
        if miss_score > 0:
            hypotheses.append({
                "cause": "cache_miss_storm",
                "score": round(miss_score, 4),
                "summary": ("prefix-cache hit ratio fell "
                            f"{pre_hit:.2f} -> {post_hit:.2f} across "
                            "the window — saved prefill compute is "
                            "back on the chip"),
                "evidence": {"pre_hit_ratio": round(pre_hit, 4),
                             "post_hit_ratio": round(post_hit, 4)}})

    # -- eviction_thrash -------------------------------------------------
    storms = int(trigger_counts.get("eviction_storm", 0)) + \
        int(trigger_counts.get("faultback_storm", 0))
    thrash_score = _clamp01(0.5 + 0.2 * storms) if storms else 0.0
    thrash_ev: Dict[str, Any] = {"storm_triggers": storms}
    occupancy = _max_pool_occupancy(evidence)
    if occupancy is not None:
        thrash_ev["pool_occupancy_ratio"] = round(occupancy, 4)
        if storms and occupancy >= 0.9:
            thrash_score = _clamp01(thrash_score + 0.15)
    if thrash_score > 0:
        hypotheses.append({
            "cause": "eviction_thrash",
            "score": round(thrash_score, 4),
            "summary": (f"{storms} eviction/fault-back storm "
                        "detections in the window — KV state is "
                        "churning faster than requests finish"),
            "evidence": thrash_ev})

    # -- recompile_host_sync ---------------------------------------------
    sanitizer = int(trigger_counts.get("sanitizer", 0))
    if sanitizer:
        kinds: Dict[str, int] = {}
        for entry in (evidence.get("flightrecorder") or {}).get(
                "pinned") or []:
            reason = str(entry.get("pinned") or "")
            if reason.startswith("sanitizer_"):
                kind = reason[len("sanitizer_"):]
                kinds[kind] = kinds.get(kind, 0) + 1
        hypotheses.append({
            "cause": "recompile_host_sync",
            "score": round(_clamp01(0.5 + 0.2 * sanitizer), 4),
            "summary": (f"{sanitizer} device-discipline violations "
                        "(recompile / host sync) on the hot path"),
            "evidence": {"sanitizer_triggers": sanitizer,
                         "violation_kinds": kinds}})

    # -- brownout_shed ---------------------------------------------------
    shed = 0
    for entry in (evidence.get("flightrecorder") or {}).get(
            "pinned") or []:
        if entry.get("pinned") == "unavailable" or \
                entry.get("status") == 503:
            shed += 1
    router = evidence.get("router") or {}
    level = max([0] + [int(v) for v in
                       (router.get("brownout_levels") or {}).values()])
    if shed or level:
        hypotheses.append({
            "cause": "brownout_shed",
            "score": round(_clamp01(0.3 + 0.1 * shed + 0.2 * level), 4),
            "summary": (f"{shed} shed/unavailable requests pinned"
                        + (f", brownout level {level} active"
                           if level else "")),
            "evidence": {"shed_pins": shed, "brownout_level": level}})

    # -- failover --------------------------------------------------------
    failovers = int(trigger_counts.get("failover", 0))
    if failovers:
        hypotheses.append({
            "cause": "failover",
            "score": round(_clamp01(0.6 + 0.2 * failovers), 4),
            "summary": (f"{failovers} replica failover / swap-failure "
                        "events — lost capacity, not slow compute"),
            "evidence": {"failover_triggers": failovers}})

    hypotheses.sort(key=lambda h: (-h["score"], h["cause"]))
    return hypotheses


def _max_pool_occupancy(evidence: Dict[str, Any]) -> Optional[float]:
    """Worst per-model pool occupancy from the cache snapshot."""
    cache = evidence.get("cache") or {}
    worst: Optional[float] = None
    for snap in (cache.get("models") or {}).values():
        paged = (snap or {}).get("paged")
        pool = (snap or {}).get("pool") or {}
        occ = None
        if isinstance(paged, dict):
            occ = paged.get("pool_occupancy_ratio")
        if occ is None:
            occ = pool.get("pool_occupancy_ratio")
        if isinstance(occ, (int, float)):
            worst = occ if worst is None else max(worst, occ)
    return worst
