"""Live roofline telemetry: engine FLOP / bandwidth / goodput gauges.

The engines have carried FLOP and padding-waste accounting in their
`stats()` dicts since PR 0 (jax_engine's cost-model MFU) and this PR
(the generator's decode/prefill FLOP, KV-working-set bandwidth, and
goodput accounting) — but stats dicts are an offline artifact: bench
scripts read them after the run.  This module *promotes* them into
process-registry gauges at `/metrics` scrape time, so the running
server continuously exposes the numbers ROADMAP item 1 derives
offline, federated through the router under a `replica` label like
every PR-2 series:

    kfserving_tpu_engine_mfu{model,phase}         achieved/peak FLOP/s
    kfserving_tpu_engine_achieved_tflops{model,phase}
    kfserving_tpu_engine_padding_waste_ratio{model,bucket}
    kfserving_tpu_engine_goodput_ratio{model}     useful tokens over
                                                  useful + garbage-wave
    kfserving_tpu_engine_hbm_bw_util_ratio{model} decode KV+param read
                                                  rate over peak HBM BW

`publish_gauges` consumes the stat keys it owns and returns them, so
the server's generic engine-stats exporter (server/app.py `_metrics`)
never double-declares the same family under a second registry.

Peak HBM bandwidth mirrors jax_engine.device_peak_flops: a per-chip
table with a `KFS_PEAK_HBM_BW` override (bytes/s), returning None on
unknown backends so the utilization gauge is omitted rather than
faked.
"""

import logging
import os
from typing import Any, Dict, Optional, Set

from kfserving_tpu.observability import metrics as obs

logger = logging.getLogger("kfserving_tpu.profiling.roofline")


def device_peak_hbm_bw() -> Optional[float]:
    """Peak HBM bandwidth (bytes/s) of the serving chip, for the
    decode bandwidth-utilization gauge.  Override with
    KFS_PEAK_HBM_BW; None when unknown (CPU backend)."""
    env = os.getenv("KFS_PEAK_HBM_BW")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    for marker, bw in (("v5 lite", 819e9), ("v5e", 819e9),
                       ("v5p", 2765e9), ("v6", 1640e9),
                       ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9)):
        if marker in kind:
            return bw
    return None


def _clamp01(value: float) -> float:
    return min(1.0, max(0.0, float(value)))


# stats() keys this module owns, per phase, mapped onto the gauge
# families above.  (key, phase) for the MFU/TFLOPs pairs.
_MFU_KEYS = (("mfu", "infer"), ("decode_mfu", "decode"),
             ("prefill_mfu", "prefill"))
_TFLOPS_KEYS = (("achieved_tflops", "infer"),
                ("achieved_decode_tflops", "decode"),
                ("achieved_prefill_tflops", "prefill"))
_WASTE_KEYS = ("bucket_pad_waste", "prefill_bucket_pad_waste")


def publish_gauges(model: str, stats: Dict[str, Any]) -> Set[str]:
    """Publish an engine stats dict's roofline numbers as registry
    gauges labeled by model.  Returns the stat keys consumed (the
    caller's generic per-key exporter must skip them — the same
    family declared from two registries would abort strict scrapes).
    Never raises into the scrape path."""
    consumed: Set[str] = set()
    try:
        for key, phase in _MFU_KEYS:
            value = stats.get(key)
            if isinstance(value, (int, float)):
                obs.engine_mfu().labels(
                    model=model, phase=phase).set(float(value))
                consumed.add(key)
        for key, phase in _TFLOPS_KEYS:
            value = stats.get(key)
            if isinstance(value, (int, float)):
                obs.engine_achieved_tflops().labels(
                    model=model, phase=phase).set(float(value))
                consumed.add(key)
        for key in _WASTE_KEYS:
            waste = stats.get(key)
            if isinstance(waste, dict):
                for bucket, value in waste.items():
                    if isinstance(value, (int, float)):
                        obs.engine_padding_waste_ratio().labels(
                            model=model, bucket=str(bucket)).set(
                                _clamp01(value))
                consumed.add(key)
        value = stats.get("goodput_ratio")
        if isinstance(value, (int, float)):
            obs.engine_goodput_ratio().labels(model=model).set(
                _clamp01(value))
            consumed.add("goodput_ratio")
        value = stats.get("hbm_bw_util")
        if isinstance(value, (int, float)):
            obs.engine_hbm_bw_util_ratio().labels(model=model).set(
                _clamp01(value))
            consumed.add("hbm_bw_util")
    except Exception:  # telemetry must never fail a scrape
        logger.exception("roofline gauge publish failed for %s", model)
    return consumed
