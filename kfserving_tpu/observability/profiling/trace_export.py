"""Chrome-trace / Perfetto export of the engine event timeline.

Renders `EngineTimeline` events as the Chrome Trace Event JSON format
(the `{"traceEvents": [...]}` object form) — loadable directly in
Perfetto (ui.perfetto.dev) or chrome://tracing:

- one *process* per replica (pid; the router's federated view re-pids
  each replica's trace and names the process after the replica host);
- *threads* are the timeline tracks: host (tid 1), device (tid 2),
  and one per engine slot (tid 10+slot) so concurrent streams render
  as parallel lanes;
- complete events (`ph: "X"`, microsecond ts/dur) for spans, instant
  events (`ph: "i"`) for zero-duration markers (preemptions,
  suppressed waves, compile-cache misses), counter events (`ph: "C"`)
  for pool-occupancy samples;
- every event's `args` carries its trace id (when the event belongs
  to a request), so a Perfetto search on the id from `/debug/traces`
  or a flight-recorder pin lands on the exact wave/chunk slices that
  served it.

`summarize()` is the bench-side consumer: dispatch-gap percentiles
(device idle between consecutive device slices), total growth-HOLD
time, and the suppressed-wave ratio, derived from the same events the
trace renders — the committed BENCH record and the Perfetto view can
never disagree.
"""

from typing import Any, Dict, List, Optional, Tuple

from kfserving_tpu.observability.profiling.timeline import (
    COUNTER,
    DEVICE,
    HOST,
    SLOT,
    Event,
)

_TID_HOST = 1
_TID_DEVICE = 2
_TID_SLOT_BASE = 10


def _tid(track: str, slot: int) -> int:
    if track == DEVICE:
        return _TID_DEVICE
    if track == SLOT and slot >= 0:
        return _TID_SLOT_BASE + slot
    return _TID_HOST


def to_chrome_trace(events: List[Event], pid: int = 1,
                    process_name: str = "kfserving-tpu"
                    ) -> Dict[str, Any]:
    """Render timeline events as a Chrome Trace Event JSON object."""
    out: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    tids_seen: Dict[int, str] = {}
    for start, dur, track, name, trace_id, slot, attrs in events:
        ts_us = start * 1e6
        if track == COUNTER:
            # Counter samples: numeric attrs become stacked series.
            vals = {k: v for k, v in (attrs or {}).items()
                    if isinstance(v, (int, float))}
            if vals:
                out.append({"ph": "C", "name": name, "pid": pid,
                            "tid": _TID_HOST, "ts": ts_us,
                            "args": vals})
            continue
        tid = _tid(track, slot)
        if tid not in tids_seen:
            tids_seen[tid] = (
                "host" if tid == _TID_HOST else
                "device" if tid == _TID_DEVICE else
                f"slot {tid - _TID_SLOT_BASE}")
        args: Dict[str, Any] = dict(attrs) if attrs else {}
        if trace_id is not None:
            args["trace_id"] = trace_id
        if slot >= 0:
            args.setdefault("slot", slot)
        event: Dict[str, Any] = {
            "name": name, "cat": track, "pid": pid, "tid": tid,
            "ts": ts_us, "args": args,
        }
        if dur > 0:
            event["ph"] = "X"
            event["dur"] = dur * 1e6
        else:
            event["ph"] = "i"
            event["s"] = "t"  # thread-scoped instant
        out.append(event)
    for tid, tname in sorted(tids_seen.items()):
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid, "args": {"name": tname}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def merge_traces(traces: List[Tuple[str, Dict[str, Any]]]
                 ) -> Dict[str, Any]:
    """Merge per-replica Chrome traces into one: each replica becomes
    its own process (re-pid'd, process_name prefixed with the host) so
    Perfetto shows the fleet as parallel process groups."""
    merged: List[Dict[str, Any]] = []
    for idx, (host, trace) in enumerate(traces):
        pid = idx + 1
        for event in trace.get("traceEvents", []):
            event = dict(event, pid=pid)
            if event.get("ph") == "M" and \
                    event.get("name") == "process_name":
                inner = dict(event.get("args") or {})
                inner["name"] = f"{host} · {inner.get('name', '')}"
                event["args"] = inner
            merged.append(event)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def _percentile(values: List[float], q: float) -> float:
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(len(ordered) * q))
    return ordered[idx]


def summarize(events: List[Event]) -> Dict[str, Any]:
    """Timeline-derived device-path summary for bench records:

    - dispatch_gap p50/p99: idle ms between consecutive device-track
      slices — the stat ROADMAP item 1's arithmetic needs (how much of
      wall clock the device actually sat waiting on the host);
    - hold_ms: total growth-starvation HOLD window time;
    - suppressed_wave_ratio: waves the adaptive governor refused vs
      dispatched decode waves;
    - slice counts per kind (waves, chunks, prefills, preemptions).
    """
    device = sorted(
        ((start, dur) for start, dur, track, *_ in events
         if track == DEVICE and dur > 0))
    gaps_ms: List[float] = []
    prev_end: Optional[float] = None
    for start, dur in device:
        if prev_end is not None:
            gaps_ms.append(max(0.0, (start - prev_end) * 1000.0))
        prev_end = max(prev_end or 0.0, start + dur)
    waves = sum(1 for _, _, t, n, *_ in events
                if t == DEVICE and n == "decode.wave")
    chunks = sum(1 for _, _, t, n, *_ in events
                 if t == DEVICE and n == "prefill.chunk")
    prefills = sum(1 for _, _, t, n, *_ in events
                   if t == DEVICE and n == "prefill.bucket")
    preempts = sum(1 for _, _, t, n, *_ in events
                   if t == HOST and n == "preempt")
    suppressed = sum(1 for _, _, t, n, *_ in events
                     if t == HOST and n == "wave.suppressed")
    hold_ms = sum(dur for _, dur, t, n, *_ in events
                  if t == HOST and n == "hold") * 1000.0
    out: Dict[str, Any] = {
        "decode_waves": waves,
        "prefill_chunks": chunks,
        "prefill_dispatches": prefills,
        "preemptions": preempts,
        "suppressed_waves": suppressed,
        "suppressed_wave_ratio": round(
            suppressed / (suppressed + waves), 4)
        if suppressed + waves else 0.0,
        "hold_ms": round(hold_ms, 3),
    }
    if gaps_ms:
        out["dispatch_gap_p50_ms"] = round(_percentile(gaps_ms, 0.50),
                                           3)
        out["dispatch_gap_p99_ms"] = round(_percentile(gaps_ms, 0.99),
                                           3)
    return out
