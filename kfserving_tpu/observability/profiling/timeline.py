"""Engine event timeline: a bounded, allocation-light ring of
device-path events.

PRs 2-3 instrumented the *request* path (spans, stage histograms, the
flight recorder); the engine's wave/chunk/preemption machinery stayed
invisible at runtime — a p99 outlier pin showed the request's stages
but not *which* decode wave, prefill chunk, growth-HOLD window, or
preemption produced them.  This ring records every generator/engine
event with wall-clock start + duration, a track (host / device /
per-slot), and the owning request's trace id, so:

- `GET /debug/profile` renders it as a Chrome-trace/Perfetto timeline
  (trace_export.py);
- pinned flight-recorder entries embed the engine events overlapping
  the request's span (monitoring/__init__.py);
- bench runs derive dispatch-gap / HOLD / suppressed-wave summaries
  from it (trace_export.summarize).

Hot-path contract (the generator records from its scheduler loop and
its enqueue/fetch executor threads):

- **never blocks**: `record()` does O(1) work — one tuple build and a
  ring-slot store under a lock held for two statements.  No I/O, no
  resizing, no iteration.
- **bounded memory**: the ring is preallocated at `capacity` slots and
  overwrites oldest-first; a sustained event storm changes *which*
  events survive, never how much memory the ring holds.
- **reader-safe**: `snapshot()`/`window()` copy the slot references
  under the same lock; concurrent writers keep rotating underneath
  without invalidating the copy (events are immutable tuples).

Knobs: `KFS_TIMELINE_EVENTS` sizes the process ring (default 8192;
one decode wave records ~2 + active-slot events, so the default holds
minutes of steady decode).
"""

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_CAPACITY = 8192

# Track names.  "host" and "device" are the two shared tracks; slot
# events carry track="slot" plus the slot index; "counter" events are
# point-in-time occupancy samples the exporter renders as Chrome
# counter series.
HOST, DEVICE, SLOT, COUNTER = "host", "device", "slot", "counter"

# Event tuple layout (immutable — readers copy references, writers
# never mutate a published event):
#   (start_epoch_s, dur_s, track, name, trace_id, slot, attrs)
Event = Tuple[float, float, str, str, Optional[str], int,
              Optional[Dict[str, Any]]]


class EngineTimeline:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(16, int(capacity))
        self._ring: List[Optional[Event]] = [None] * self.capacity
        self._next = 0          # total events ever recorded
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "EngineTimeline":
        try:
            cap = int(os.environ.get("KFS_TIMELINE_EVENTS",
                                     DEFAULT_CAPACITY))
        except ValueError:
            cap = DEFAULT_CAPACITY
        return cls(cap)

    # -- writing (hot path) ------------------------------------------------
    def record(self, track: str, name: str, dur_s: float = 0.0,
               trace_id: Optional[str] = None, slot: int = -1,
               t_end: Optional[float] = None,
               attrs: Optional[Dict[str, Any]] = None) -> None:
        """Record one event ending at `t_end` (default: now) that ran
        for `dur_s` seconds (0 = instant).  `attrs` is stored by
        reference and must not be mutated after the call."""
        end = time.time() if t_end is None else t_end
        event: Event = (end - dur_s, dur_s, track, name, trace_id,
                        int(slot), attrs)
        with self._lock:
            self._ring[self._next % self.capacity] = event
            self._next += 1

    def counter(self, name: str, values: Dict[str, Any]) -> None:
        """Point-in-time occupancy sample (free blocks, active slots,
        pending depth) — rendered as a Chrome counter track."""
        self.record(COUNTER, name, attrs=values)

    # -- reading -----------------------------------------------------------
    @property
    def recorded(self) -> int:
        return self._next

    def snapshot(self, window_s: Optional[float] = None,
                 now: Optional[float] = None) -> List[Event]:
        """Events oldest-first, optionally only those whose span ends
        inside the trailing `window_s` seconds."""
        with self._lock:
            n = self._next
            if n <= self.capacity:
                events = [e for e in self._ring[:n]]
            else:
                head = n % self.capacity
                events = self._ring[head:] + self._ring[:head]
        events = [e for e in events if e is not None]
        if window_s is not None:
            cutoff = (now if now is not None else time.time()) \
                - float(window_s)
            events = [e for e in events if e[0] + e[1] >= cutoff]
        return events

    def window(self, t0: float, t1: float, limit: int = 64
               ) -> List[Dict[str, Any]]:
        """Events overlapping [t0, t1] as dicts (newest `limit`), for
        embedding in flight-recorder entries.  Tuples are filtered and
        sliced BEFORE dict conversion — this runs on every pin, and
        dict-ifying a full ring to keep 64 would tax exactly the
        tail-latency storms pins exist for."""
        limit = max(0, int(limit))
        if limit == 0:
            return []
        hits = [e for e in self.snapshot()
                if e[0] <= t1 and e[0] + e[1] >= t0]
        return [self.event_dict(e) for e in hits[-limit:]]

    @staticmethod
    def event_dict(event: Event) -> Dict[str, Any]:
        start, dur, track, name, trace_id, slot, attrs = event
        out: Dict[str, Any] = {
            "t": round(start, 6),
            "dur_ms": round(dur * 1000.0, 3),
            "track": track,
            "name": name,
        }
        if trace_id is not None:
            out["trace_id"] = trace_id
        if slot >= 0:
            out["slot"] = slot
        if attrs:
            out["attrs"] = dict(attrs)
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._next = 0


# The process timeline: one serving process = one device path = one
# event ring (the same singleton shape as tracing.tracer).
TIMELINE = EngineTimeline.from_env()
