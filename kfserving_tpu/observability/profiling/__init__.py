"""Device-time observability: engine timeline, trace export, roofline.

The profiling layer turns ROADMAP item 1's "re-derive the arithmetic
at real step times" from a one-off offline exercise into something the
running server exposes continuously:

- `timeline`     — the bounded, allocation-light engine event ring
                   (decode waves, prefill chunks, preemptions,
                   growth-HOLD windows, compile-cache misses, device
                   dispatch spans, pool occupancy), trace-id
                   correlated with the PR-2 spans;
- `trace_export` — Chrome-trace/Perfetto rendering of the ring
                   (`GET /debug/profile?window_s=&format=trace_json`),
                   fleet merge for the router's federated view, and
                   the bench-side dispatch-gap/HOLD summary;
- `roofline`     — promotion of the engines' FLOP / bucket-waste /
                   bandwidth accounting into registry gauges
                   (`kfserving_tpu_engine_mfu`, padding-waste and
                   goodput ratios, decode HBM-bandwidth utilization),
                   federated through the router like all PR-2 series.

Import discipline (observability package contract): nothing from
`server/`, `control/`, `engine/`, or `reliability/` — the engines
record *into* this layer, never the reverse.
"""

from kfserving_tpu.observability.profiling.timeline import (
    TIMELINE,
    EngineTimeline,
)
from kfserving_tpu.observability.profiling.trace_export import (
    merge_traces,
    summarize,
    to_chrome_trace,
)

__all__ = ["TIMELINE", "EngineTimeline", "to_chrome_trace",
           "merge_traces", "summarize"]
