"""Fleet /metrics federation: relabel replica scrapes for the router.

The ingress router exposes one `/metrics` that appends every replica's
scrape with a `replica="host:port"` label injected into each sample, so
a single Prometheus target sees the whole fleet (the federation shape
Knative gets from per-pod scrape configs).  The rewriter must survive
label values containing braces/quotes and OpenMetrics exemplar
suffixes, so it scans the label block character-wise instead of
regexing the line.
"""

from typing import Dict, List, Optional, Tuple

from kfserving_tpu.observability.registry import escape_label_value


def split_sample(line: str) -> Optional[Tuple[str, str, str]]:
    """Split a sample line into (name, label_block_inner, rest).

    `rest` is everything after the label block (value, and any
    exemplar suffix), leading space stripped.  Returns None for lines
    that are not samples (comments, blanks, malformed)."""
    line = line.rstrip()
    if not line or line.startswith("#"):
        return None
    brace = line.find("{")
    space = line.find(" ")
    if brace == -1 or (space != -1 and space < brace):
        if space == -1:
            return None
        return line[:space], "", line[space + 1:].lstrip()
    name = line[:brace]
    i = brace + 1
    in_quotes = False
    escaped = False
    while i < len(line):
        c = line[i]
        if escaped:
            escaped = False
        elif c == "\\":
            escaped = True
        elif c == '"':
            in_quotes = not in_quotes
        elif c == "}" and not in_quotes:
            return name, line[brace + 1:i], line[i + 1:].lstrip()
        i += 1
    return None


def relabel(text: str, extra: Dict[str, str],
            seen_meta: Optional[set] = None,
            keep_exemplars: bool = True) -> List[str]:
    """Rewrite a /metrics payload, injecting `extra` labels into every
    sample line.  # HELP / # TYPE lines pass through once per metric
    name across calls (share `seen_meta` between replicas so the
    merged output never re-declares a family).  ``keep_exemplars=
    False`` strips OpenMetrics exemplar suffixes — required when the
    merged output is served as classic text/plain, whose parser
    rejects them."""
    prefix = ",".join(f'{k}="{escape_label_value(v)}"'
                      for k, v in sorted(extra.items()))
    out: List[str] = []
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            if seen_meta is not None:
                parts = line.split(" ", 3)
                key = (parts[1], parts[2]) if len(parts) > 2 else line
                if key in seen_meta:
                    continue
                seen_meta.add(key)
            out.append(line)
            continue
        parsed = split_sample(line)
        if parsed is None:
            continue
        name, inner, rest = parsed
        if not keep_exemplars:
            # `rest` is "<value> [# {exemplar} v ts]"; the value itself
            # never contains " # ".
            rest = rest.split(" # ", 1)[0]
        labels = prefix + ("," + inner if inner else "")
        out.append(f"{name}{{{labels}}} {rest}")
    return out


def merge_scrapes(own_lines: List[str],
                  scrapes: List[Tuple[str, str]],
                  keep_exemplars: bool = True) -> List[str]:
    """Merge the router's own exposition with replica scrapes into ONE
    valid payload: every family declared exactly once, with ALL of its
    samples (own + every replica's, relabeled) contiguous under the
    declaration — the shape strict OpenMetrics parsers require (a
    naive concatenation re-declares shared families per replica, and a
    TYPE-deduped concatenation scatters a family's samples, both of
    which abort the whole scrape).

    In-process deployments share one registry between router and
    replicas, so shared series appear both bare and replica-labeled —
    a dev-mode artifact; subprocess replicas have disjoint registries.
    """
    # family name -> {"meta": [...], "samples": [...]}; insertion order
    # is emission order.
    families: Dict[str, Dict[str, List[str]]] = {}
    seen_meta: set = set()

    def feed(lines: List[str]):
        current = None
        for line in lines:
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                # "# HELP <name> <text>" / "# TYPE <name> <kind>"
                parts = line.split(" ", 3)
                if len(parts) < 3:
                    continue
                kind, current = parts[1], parts[2]
                fam = families.setdefault(current,
                                          {"meta": [], "samples": []})
                if (current, kind) not in seen_meta:
                    seen_meta.add((current, kind))
                    fam["meta"].append(line)
                continue
            parsed = split_sample(line)
            if parsed is None:
                continue
            name = parsed[0]
            # Histogram _bucket/_sum/_count samples group under their
            # declared base family; anything else is its own family.
            fam_name = (current if current is not None
                        and name.startswith(current) else name)
            families.setdefault(fam_name,
                                {"meta": [], "samples": []})[
                "samples"].append(line)

    feed(own_lines)
    for host, text in scrapes:
        feed(relabel(text, {"replica": host},
                     keep_exemplars=keep_exemplars))
    out: List[str] = []
    for fam in families.values():
        out += fam["meta"]
        out += fam["samples"]
    return out
