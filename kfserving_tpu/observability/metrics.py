"""The metric catalog: accessors for every cross-layer instrument.

Each accessor re-resolves its family from the process registry on
every call (registration is an idempotent dict lookup), so a
test-time `REGISTRY.reset()` can never leave a caller holding a stale
instrument.  Layers call e.g.::

    from kfserving_tpu.observability import metrics as obs

    obs.batch_queue_wait_ms().labels(bucket=str(key)).observe(age_ms)
    obs.llm_ttft_ms().observe(ttft, trace_id=req.trace_id)

Series naming follows the seed's `kfserving_tpu_` prefix; histograms
are milliseconds unless the name says otherwise.
"""

from kfserving_tpu.observability.registry import (
    LATENCY_BUCKETS_MS,
    RATIO_BUCKETS,
    REGISTRY,
    THROUGHPUT_BUCKETS,
)

# The per-request accounting series every consumer keys on: the
# server's Metrics feeds them, the recycling watchdog scrapes the
# counter by literal name, and the SLO engine reads both.  They live
# HERE (the lowest observability layer) so upper layers share one
# constant instead of re-declaring the literal — a rename that skips
# a consumer would silently disable request-count recycling or zero
# every SLO burn rate.
REQUEST_TOTAL_SERIES = "kfserving_tpu_request_total"
REQUEST_LATENCY_SERIES = "kfserving_tpu_request_latency_ms"

# Per-revision request series the router feeds and the rollout
# analyzer (control/rollout.py) gates on — shared constants for the
# same skipped-consumer reason as above.
REVISION_REQUESTS_SERIES = "kfserving_tpu_revision_requests_total"
REVISION_LATENCY_SERIES = "kfserving_tpu_revision_request_ms"

# The trend-slope gauge the history detector exports and the
# predictive scaler's slope-aware sizing reads back — shared constant
# so the producer/consumer pair can't drift apart.
TREND_SLOPE_SERIES = "kfserving_tpu_trend_slope_per_second"


# -- batcher ------------------------------------------------------------
def batch_queue_wait_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_batch_queue_wait_ms",
        "Time a request's oldest instance waited in the dynamic "
        "batcher queue before its batch flushed")


def batch_fill_ratio():
    return REGISTRY.histogram(
        "kfserving_tpu_batch_fill_ratio",
        "Flushed batch size as a fraction of the executed bucket "
        "(1.0 = zero pad slots)", buckets=RATIO_BUCKETS)


# -- engine -------------------------------------------------------------
def engine_stage_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_engine_stage_ms",
        "Per-execution engine stage timing (stage=prepare|transfer|"
        "compute|fetch)")


def compile_cache_events():
    return REGISTRY.counter(
        "kfserving_tpu_compile_cache_total",
        "Compiled-executable cache lookups by outcome (outcome=hit "
        "means the shape was already compiled; miss paid a compile)")


# -- LLM generation -----------------------------------------------------
def llm_ttft_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_llm_ttft_ms",
        "Time from generation submit to the first emitted token")


def llm_inter_token_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_llm_inter_token_ms",
        "Gap between consecutive emitted tokens of one generation")


def llm_tokens_per_second():
    return REGISTRY.histogram(
        "kfserving_tpu_llm_tokens_per_second",
        "Whole-generation decode throughput at finish",
        buckets=THROUGHPUT_BUCKETS)


def llm_tokens_total():
    return REGISTRY.counter(
        "kfserving_tpu_llm_tokens_total",
        "Prompt and generated tokens by direction (direction=in|out)")


def generator_prefill_chunks_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_prefill_chunks_total",
        "Chunked-prefill chunks by outcome (outcome=dispatched — one "
        "device call riding the decode FIFO; skipped_shared — every "
        "block was a prefix-cache hit, no compute dispatched)")


def generator_prefill_chunk_stall_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_generator_prefill_chunk_stall_ms",
        "Device-busy time one prefill chunk inserted between decode "
        "fetches — the stall a cold prompt adds to live streams per "
        "chunk (the monolithic-prefill stall divided by chunk count)")


def generator_pipeline_depth():
    return REGISTRY.gauge(
        "kfserving_tpu_generator_pipeline_depth",
        "Effective decode pipeline depth after the adaptive governor "
        "(configured depth when streams extend past the in-flight "
        "horizon; 1 when speculative waves could only decode garbage)")


def generator_suppressed_waves_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_suppressed_waves_total",
        "Speculative decode waves the adaptive-depth governor did not "
        "enqueue because every active stream provably finishes within "
        "the waves already in flight")


# -- prefix cache & block pool (ISSUE 13) -------------------------------
# Count-valued buckets for the cache/attribution distributions: token
# counts span prompt sizes (1..4k), block counts span pool tables, and
# reuse depth counts hits per prefix-index entry.
TOKEN_BUCKETS = [1, 4, 16, 64, 256, 1024, 4096]
BLOCK_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128]
REUSE_DEPTH_BUCKETS = [1, 2, 4, 8, 16, 32, 64]


def generator_prefix_lookups_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_prefix_lookups_total",
        "Chain-hash prefix-index probes per full prompt block at plan "
        "time, by outcome (hit = the block's k/v were already "
        "device-resident and the plan points at the shared block; "
        "host_hit = a device miss answered by the host KV tier, the "
        "block faults back instead of re-prefilling; miss = a fresh "
        "block was allocated) — the replica-side feed "
        "prefix-affinity routing reads through /metrics federation")


def generator_prefill_tokens_saved_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_prefill_tokens_saved_total",
        "Prompt tokens whose k/v came from shared prefix blocks "
        "instead of being stored again (hit blocks x block_size); "
        "chunked admissions additionally skip the compute for "
        "whole-chunk hits (generator_prefill_chunks_total{outcome="
        "\"skipped_shared\"})")


def generator_block_evictions_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_block_evictions_total",
        "Pool blocks leaving their role, by cause: capacity_spilled "
        "= LRU reclaim of a zero-ref cached prefix block under "
        "allocation pressure whose k/v landed in the host KV tier "
        "(its device index entry drops, the chain survives host-"
        "side); capacity_dropped = the same reclaim with the state "
        "lost (no tier, no chain, or a failed spill — the drop-on-"
        "evict baseline); index_invalidation = provisional prefix "
        "registrations dropped because their planned writes never "
        "dispatched (plan rollback / enqueue failure); "
        "zombie_deferral = slot blocks released after maturing "
        "through the zombie-wave deferral window (the normal free "
        "path, counted so the deferral machinery is observable)")


# -- host KV tier (engine/kv_tier.py): spilled-conversation residency
# one level under the device pool — occupancy, spill/fault outcomes,
# and the latency of faulting a returning turn's blocks back ----------
def generator_kv_tier_blocks():
    return REGISTRY.gauge(
        "kfserving_tpu_generator_kv_tier_blocks",
        "Blocks currently held by the host KV tier (spilled "
        "conversation prefixes a returning turn can fault back "
        "instead of re-prefilling)")


def generator_kv_tier_occupancy_ratio():
    return REGISTRY.gauge(
        "kfserving_tpu_generator_kv_tier_occupancy_ratio",
        "Host KV tier occupancy over its capacity (1.0 = the tier's "
        "own LRU ledger is evicting on every admission)")


def generator_kv_tier_spills_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_kv_tier_spills_total",
        "Capacity-evicted blocks offered to the host tier by "
        "outcome: spilled = payload landed and the index entry "
        "published; failed = the spill machinery failed (the "
        "eviction degraded to the drop-on-evict baseline — "
        "counted under block_evictions{cause=\"capacity_dropped\"}); "
        "duplicate = the chain was already host-resident")


def generator_kv_tier_faultbacks_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_kv_tier_faultbacks_total",
        "Host-tier blocks a returning turn's admission plan claimed, "
        "by outcome: faulted = one physical read + pool insert; "
        "coalesced = a concurrent plan rode an in-flight fault "
        "(single-flight); failed = the fault-back failed and the "
        "turn fell through to a normal re-prefill")


def generator_kv_tier_faultback_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_generator_kv_tier_faultback_ms",
        "Latency of one fault-back batch (mmap read + jitted pool "
        "insert enqueue) — the milliseconds a returning turn paid "
        "instead of a full re-prefill")


def generator_kv_tier_evictions_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_kv_tier_evictions_total",
        "Host-tier entries leaving the ledger by reason: capacity = "
        "LRU eviction admitting a newer spill; skipped_inflight = an "
        "eviction vetoed because the victim was mid-fault-in "
        "(admission-aware, the hbm.py victim_ok discipline); "
        "faultback_failed = entry dropped because its read failed "
        "(the payload is suspect — the turn re-prefills)")


def generator_kv_tier_tokens_saved_total():
    return REGISTRY.counter(
        "kfserving_tpu_generator_kv_tier_tokens_saved_total",
        "Prompt tokens served from the host KV tier instead of "
        "re-prefilled (host-hit blocks x block_size) — the host-"
        "side twin of generator_prefill_tokens_saved_total, kept "
        "distinct so the drop-vs-spill economics stay attributable")


# -- KV handoff (ISSUE 19): conversation state surviving the replica
# process — drain-parachute exports, manifest re-attach adoption, and
# the replica-to-replica peer transfer path ---------------------------
def kv_handoff_exported_blocks_total():
    return REGISTRY.counter(
        "kfserving_tpu_kv_handoff_exported_blocks_total",
        "Device KV blocks offered to the durable host tier by the "
        "drain parachute (SIGTERM / swap-window export of live slots "
        "and hot prefix chains), by outcome: exported = payload "
        "landed in the tier; skipped = already host-resident; "
        "dropped = the drain budget deadline passed first (hottest-"
        "first order, so drops are the coldest tail — counted, never "
        "hidden); failed = the export machinery failed (chaos site "
        "engine.kv_export or a gather/fetch error)")


def kv_handoff_reattached_blocks_total():
    return REGISTRY.counter(
        "kfserving_tpu_kv_handoff_reattached_blocks_total",
        "Predecessor-generation tier entries processed on re-attach "
        "(boot-time adoption or POST /kv/reattach), by outcome: "
        "adopted = digest-verified and admitted as a warm fault-"
        "back; duplicate = already resident; corrupt = payload "
        "digest mismatch (entry self-deleted, never served); "
        "truncated = payload file short of the recorded slot; torn "
        "= unparseable manifest line (crash mid-append); "
        "version_skew = record schema version unknown to this "
        "build; dropped_capacity = adoption never evicts the "
        "successor's own live entries; failed = admission failed")


def kv_handoff_peer_blocks_total():
    return REGISTRY.counter(
        "kfserving_tpu_kv_handoff_peer_blocks_total",
        "KV blocks pulled over the replica-to-replica transfer path "
        "(GET /kv/chains/<chain> on the predecessor named by the "
        "router's failover hint), by outcome: imported = digest-"
        "verified on receipt and admitted; digest_mismatch = wire "
        "payload failed verification (discarded, never served); "
        "skipped = already resident locally; failed = fetch error "
        "or the engine.kv_import chaos site (the turn degrades to a "
        "clean re-prefill)")


def kv_handoff_export_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_kv_handoff_export_ms",
        "Wall time of one drain-parachute export pass (gather + D2H "
        "fetch + tier writes for all surviving candidates) — must "
        "sit inside the drain budget (KFS_KV_EXPORT_BUDGET_S), "
        "never stretch the swap window")


def generator_prefix_reuse_depth_hits():
    return REGISTRY.histogram(
        "kfserving_tpu_generator_prefix_reuse_depth_hits",
        "Cumulative hit count of a prefix-index entry at each hit "
        "(observed per hit event: an entry hit for the Nth time "
        "lands in the N bucket) — deep entries are hot shared "
        "system prompts, the routing-affinity signal",
        buckets=REUSE_DEPTH_BUCKETS)


def generator_pool_occupancy_ratio():
    return REGISTRY.gauge(
        "kfserving_tpu_generator_pool_occupancy_ratio",
        "Referenced (ref > 0) blocks over the whole pool at the last "
        "scrape — 1.0 means every block is held by a live slot or "
        "shared prefix; reclaimable cached blocks do not count")


def generator_pool_fragmentation_ratio():
    return REGISTRY.gauge(
        "kfserving_tpu_generator_pool_fragmentation_ratio",
        "Internal fragmentation of slot tables: 1 - resident tokens "
        "/ (table blocks x block_size), with shared prefix blocks "
        "counted per sharer on both sides — the tail positions "
        "allocated for growth but not yet holding k/v")


# -- HBM residency (engine/hbm.py accountant) ---------------------------
def hbm_resident_bytes():
    return REGISTRY.gauge(
        "kfserving_tpu_hbm_resident_bytes",
        "Accounted HBM residency per model (params + cache pool as "
        "admitted to the HBMManager budget); series are pruned when "
        "the model is released")


def hbm_budget_bytes():
    return REGISTRY.gauge(
        "kfserving_tpu_hbm_budget_bytes",
        "The HBMManager's packing budget for this device/mesh")


def hbm_evictions_total():
    return REGISTRY.counter(
        "kfserving_tpu_hbm_evictions_total",
        "Models evicted from HBM residency by the LRU accountant to "
        "fit an admission, labeled by the evicted model")


def hbm_eviction_skips_total():
    return REGISTRY.counter(
        "kfserving_tpu_hbm_eviction_skips_total",
        "LRU eviction candidates the admission plan passed over, by "
        "skipped model and reason (busy = the residency manager vetoed "
        "a victim with queued or in-flight work — the admission-aware "
        "guarantee that a serving model is never yanked from HBM)")


# -- model residency (engine/residency.py) ------------------------------
def residency_state():
    return REGISTRY.gauge(
        "kfserving_tpu_residency_state",
        "Per-model residency state (0=registered, 1=host-resident "
        "mmap-backed, 2=fault-in in flight, 3=HBM-resident serving); "
        "series are pruned when the model deregisters")


def residency_fault_in_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_residency_fault_in_ms",
        "Fault-in latency of a predict that found its model outside "
        "HBM, by source (warm = host mmap params re-placed on device; "
        "cold = first activation paying download/materialize/compile)")


def residency_fault_ins_total():
    return REGISTRY.counter(
        "kfserving_tpu_residency_fault_ins_total",
        "Residency fault-ins by model and outcome (warm|cold = one "
        "physical transfer; coalesced = a concurrent request rode an "
        "already-in-flight fault instead of issuing its own; error = "
        "the fault failed and the incumbent resident set kept serving)")


# -- per-request cost attribution (observability/attribution.py) --------
def request_device_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_request_device_ms",
        "Per-request attributed device time by phase (prefill|"
        "decode): each dispatch's busy interval is split evenly "
        "across the live streams it served, so the series sums to "
        "total device time (the InferLine-style per-stage cost the "
        "provisioning math consumes)")


def request_phase_tokens():
    return REGISTRY.histogram(
        "kfserving_tpu_request_phase_tokens",
        "Per-request token counts by phase (prefill = prompt tokens "
        "ingested, decode = tokens generated)",
        buckets=TOKEN_BUCKETS)


def request_held_blocks():
    return REGISTRY.histogram(
        "kfserving_tpu_request_held_blocks",
        "Peak pool blocks a request's slot table held (paged mode; "
        "prompt + growth horizon) — the residency cost of admitting "
        "this request",
        buckets=BLOCK_BUCKETS)


def request_cache_saved_tokens():
    return REGISTRY.histogram(
        "kfserving_tpu_request_cache_saved_tokens",
        "Prompt tokens a request did not re-store thanks to prefix-"
        "cache hits (hit blocks x block_size; 0 = fully cold)",
        buckets=TOKEN_BUCKETS)


def request_host_tier_saved_tokens():
    return REGISTRY.histogram(
        "kfserving_tpu_request_host_tier_saved_tokens",
        "Prompt tokens a request served from the host KV tier "
        "(fault-back) instead of re-prefilling — distinct from "
        "request_cache_saved_tokens (device prefix hits) so the "
        "per-request cost record shows WHICH tier earned the "
        "savings; the two are additive",
        buckets=TOKEN_BUCKETS)


# -- engine roofline (fed by observability/profiling/roofline.py at
# /metrics scrape time from the engines' stats dicts) -------------------
def engine_mfu():
    return REGISTRY.gauge(
        "kfserving_tpu_engine_mfu",
        "Model FLOP utilization: achieved FLOP/s over the chip's "
        "peak, per phase (phase=infer — the bucketed JaxEngine path; "
        "decode|prefill — the generator's device spans).  A floor on "
        "true utilization: device seconds include the runtime round "
        "trip in non-blocking mode")


def engine_achieved_tflops():
    return REGISTRY.gauge(
        "kfserving_tpu_engine_achieved_tflops",
        "Achieved dense-compute TFLOP/s per engine phase (the MFU "
        "numerator, absolute)")


def engine_padding_waste_ratio():
    return REGISTRY.gauge(
        "kfserving_tpu_engine_padding_waste_ratio",
        "Fraction of dispatched batch/sequence slots that were "
        "bucket padding, per compiled bucket (0 = every slot carried "
        "a real token/row)")


def engine_goodput_ratio():
    return REGISTRY.gauge(
        "kfserving_tpu_engine_goodput_ratio",
        "Useful emitted tokens over useful + garbage token steps "
        "(speculative-wave decode past a finish/cancel) — the decode "
        "pipeline's goodput split")


def engine_hbm_bw_util_ratio():
    return REGISTRY.gauge(
        "kfserving_tpu_engine_hbm_bw_util_ratio",
        "Decode HBM read-bandwidth utilization estimated from the "
        "params + resident KV-cache working set per token step over "
        "the chip's peak HBM bandwidth (decode is bandwidth-bound: "
        "this is its roofline axis)")


# -- reliability --------------------------------------------------------
def breaker_state():
    return REGISTRY.gauge(
        "kfserving_tpu_breaker_state",
        "Circuit breaker state (0=closed, 1=half_open, 2=open)")


def breaker_transitions():
    return REGISTRY.counter(
        "kfserving_tpu_breaker_transitions_total",
        "Circuit breaker state transitions (to=open|closed)")


def retry_total():
    return REGISTRY.counter(
        "kfserving_tpu_retry_total",
        "Retries performed, labeled by edge (policy name) and reason "
        "(exception class)")


def deadline_exceeded_total():
    return REGISTRY.counter(
        "kfserving_tpu_deadline_exceeded_total",
        "Requests shed because their latency budget ran out, by stage")


# -- monitoring loop ----------------------------------------------------
def monitor_events_total():
    return REGISTRY.counter(
        "kfserving_tpu_monitor_events_total",
        "Monitor-bus publish outcomes (outcome=published|sampled_out|"
        "dropped; dropped = bounded queue full, serving never blocks)")


def monitor_consumer_errors_total():
    return REGISTRY.counter(
        "kfserving_tpu_monitor_consumer_errors_total",
        "Monitor consumer callbacks that raised (by consumer name); "
        "a broken monitor never breaks the bus or serving")


def monitor_alert_state():
    return REGISTRY.gauge(
        "kfserving_tpu_monitor_alert_state",
        "Per-model online monitor alert state (monitor=drift|outlier; "
        "1 = alerting)")


def drift_score():
    return REGISTRY.gauge(
        "kfserving_tpu_drift_score",
        "Max per-feature two-sample KS statistic of the live window "
        "vs the reference sample (0 = identical distributions)")


def outlier_rate():
    return REGISTRY.gauge(
        "kfserving_tpu_outlier_rate",
        "Fraction of the sliding window flagged as Mahalanobis "
        "outliers against the reference distribution")


def slo_burn_rate():
    return REGISTRY.gauge(
        "kfserving_tpu_slo_burn_rate",
        "Error-budget burn rate per model/objective/window (1.0 = "
        "spending exactly the budget; alert past the threshold)")


def slo_alert_state():
    return REGISTRY.gauge(
        "kfserving_tpu_slo_alert_state",
        "Per-model SLO alert state (1 = burn rate over threshold on "
        "every configured window)")


def slo_breaches_total():
    return REGISTRY.counter(
        "kfserving_tpu_slo_breaches_total",
        "SLO alert activations (0 -> 1 transitions) per model")


def flightrecorder_pinned_total():
    return REGISTRY.counter(
        "kfserving_tpu_flightrecorder_pinned_total",
        "Flight-recorder entries pinned, by trigger reason")


# -- telemetry history & trend detection (observability/history/) ------
def history_tick_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_history_tick_ms",
        "Wall time of one history sampler tick (walk every registry "
        "family, append rings, run the trend detector) — the "
        "sampler's own overhead, bounded by construction")


def history_tick_failures_total():
    return REGISTRY.counter(
        "kfserving_tpu_history_tick_failures_total",
        "History sampler ticks that raised (swallowed; history goes "
        "stale-but-served) — a climbing rate means the time axis is "
        "silently frozen")


def history_samples_total():
    return REGISTRY.counter(
        "kfserving_tpu_history_samples_total",
        "Points appended to the in-process history rings across all "
        "series and ticks")


def history_series():
    return REGISTRY.gauge(
        "kfserving_tpu_history_series",
        "Live series in the history ring store (bounded by "
        "KFS_HISTORY_MAX_SERIES; overflow is dropped, never grown)")


def trend_slope_per_second():
    return REGISTRY.gauge(
        TREND_SLOPE_SERIES,
        "EWMA'd first derivative of each watched history series "
        "(units of the series per second), labeled {series=family, "
        "...underlying labels} — the leading input slope-aware "
        "predictive scaling consumes")


def trend_zscore():
    return REGISTRY.gauge(
        "kfserving_tpu_trend_zscore",
        "Latest z-score of each watched history series against its "
        "EWMA baseline (|z| past the threshold for consecutive ticks "
        "declares a change-point)")


def trend_changepoints_total():
    return REGISTRY.counter(
        "kfserving_tpu_trend_changepoints_total",
        "Change-points the history trend detector declared, by "
        "watched series — each one also pins a trend_<series> "
        "flight-recorder entry embedding the pre/post window frames")


# -- payload logger -----------------------------------------------------
def payload_log_total():
    return REGISTRY.counter(
        "kfserving_tpu_payload_log_total",
        "CloudEvents payload-logger events by outcome "
        "(outcome=sent|failed|dropped)")


def payload_log_queued():
    return REGISTRY.gauge(
        "kfserving_tpu_payload_log_queued",
        "CloudEvents payload-logger queue depth")


# -- ingress router -----------------------------------------------------
def router_inflight():
    return REGISTRY.gauge(
        "kfserving_tpu_router_inflight",
        "In-flight proxied requests per component")


def router_requests_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_requests_total",
        "Requests routed per component")


def router_rotation_skips_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_rotation_skips_total",
        "Replica picks skipped because the host's breaker was open")


def router_shed_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_shed_total",
        "Requests the router shed instead of proxying, by reason")


def router_request_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_router_request_ms",
        "Router-observed request latency (proxy hop included)",
        buckets=LATENCY_BUCKETS_MS)


# -- predictive control loop (control/predictive.py + autoscaler) -------
def autoscaler_tick_failures_total():
    return REGISTRY.counter(
        "kfserving_tpu_autoscaler_tick_failures_total",
        "Autoscaler ticks that raised (the control loop swallowed the "
        "exception and kept running) — a climbing rate means the "
        "scaling loop is silently dead")


def autoscaler_decisions_total():
    return REGISTRY.counter(
        "kfserving_tpu_autoscaler_decisions_total",
        "Predictive control-loop decisions by component and action "
        "(scale_up|pre_arm|brownout_enter|brownout_exit) — every one "
        "also lands as a pinned supervisor flight-recorder record")


def autoscaler_predicted_replicas():
    return REGISTRY.gauge(
        "kfserving_tpu_autoscaler_predicted_replicas",
        "Replica count the feed-forward latency model sized for a "
        "component at the last tick (arrival rate x observed service "
        "time vs SLO headroom); 0 = the predictive path is not "
        "engaged")


def brownout_level():
    return REGISTRY.gauge(
        "kfserving_tpu_brownout_level",
        "Per-model brownout level (0 = off; level N sheds priority "
        "tiers below N with explicit retriable 503s)")


def brownout_shed_total():
    return REGISTRY.counter(
        "kfserving_tpu_brownout_shed_total",
        "Requests the brownout admission gate shed, by model and "
        "reason (priority = tier below the active level, deadline = "
        "remaining budget cannot cover the observed service time, "
        "fault = injected admission fault)")


def brownout_transitions_total():
    return REGISTRY.counter(
        "kfserving_tpu_brownout_transitions_total",
        "Brownout level transitions per model (direction=enter|"
        "escalate|recover|exit)")


# -- progressive rollout ------------------------------------------------
def revision_requests_total():
    return REGISTRY.counter(
        "kfserving_tpu_revision_requests_total",
        "Router upstream attempts per served revision (labels: model, "
        "revision, status; transport failures count as 5xx) — the "
        "per-revision series the rollout analyzer gates on")


def revision_request_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_revision_request_ms",
        "Router-observed upstream attempt latency per served revision",
        buckets=LATENCY_BUCKETS_MS)


def rollout_state():
    return REGISTRY.gauge(
        "kfserving_tpu_rollout_state",
        "Rollout state machine phase per component/revision "
        "(0=warming, 1=progressing, 2=promoted, 3=rolled_back)")


def rollout_step_percent():
    return REGISTRY.gauge(
        "kfserving_tpu_rollout_step_percent",
        "Current canary traffic percent the rollout manager has "
        "granted the component's latest revision")


def rollout_transitions_total():
    return REGISTRY.counter(
        "kfserving_tpu_rollout_transitions_total",
        "Rollout state-machine transitions by event (step|promoted|"
        "rolled_back)")


def rollout_quarantined():
    return REGISTRY.gauge(
        "kfserving_tpu_rollout_quarantined",
        "Quarantined (rolled-back) revision hashes currently "
        "remembered per component")


# -- replica lifecycle (warm standby / failover) ------------------------
def lifecycle_swaps_total():
    return REGISTRY.counter(
        "kfserving_tpu_lifecycle_swaps_total",
        "Replica recycle swaps by mode (warm_standby|exclusive_"
        "standby|overlap|cold) and outcome (ok|failed)")


def lifecycle_swap_failures_total():
    return REGISTRY.counter(
        "kfserving_tpu_lifecycle_swap_failures_total",
        "Standby swaps that aborted with the incumbent kept serving, "
        "by reason (spawn_error|activate_error|activate_timeout)")


def lifecycle_promotions_total():
    return REGISTRY.counter(
        "kfserving_tpu_lifecycle_promotions_total",
        "Crash-detected replicas replaced by standby promotion, by "
        "trigger (process_exit|health_fail|crash_report) and outcome "
        "(promoted|cold_respawn)")


# Lifecycle phases span three decades (a warm activate is hundreds of
# ms, a cold standby spawn tens of seconds) — the request-latency
# ladder tops out too low to separate a 14 s activate from a 40 s one.
LIFECYCLE_BUCKETS_MS = [50, 100, 250, 500, 1000, 2000, 5000, 10000,
                        20000, 40000, 80000]


def lifecycle_phase_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_lifecycle_phase_ms",
        "Wall time of each replica lifecycle phase (standby_spawn|"
        "activate|drain|promote)",
        buckets=LIFECYCLE_BUCKETS_MS)


def lifecycle_standby_pool():
    return REGISTRY.gauge(
        "kfserving_tpu_lifecycle_standby_pool",
        "Warm standby processes currently armed (spawned, imports + "
        "artifact done, device untouched) per component")


def router_swap_held_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_swap_held_total",
        "Requests that hit an announced swap window, by outcome "
        "(served = a replica appeared inside the hold budget, shed = "
        "bounded queue full, expired = hold budget ran out)")


def router_swap_hold_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_router_swap_hold_ms",
        "Time requests were held at the router across an announced "
        "drain->activate swap window before being served",
        buckets=LATENCY_BUCKETS_MS)


def router_affinity_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_affinity_total",
        "Affinity replica picks by key mode and outcome (mode=model "
        "hashes the model name, mode=prefix hashes the normalized "
        "prompt's first-N-block chain digest onto the same ring; "
        "ring = served at the key's primary ring position; spill = "
        "overload/breaker moved it to the next ring position; "
        "fallback = the ring yielded no host or an injected "
        "affinity-pick fault dropped the request to plain "
        "round-robin)")


def router_stream_failover_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_stream_failover_total",
        "Mid-stream upstream deaths surfaced to the client as an "
        "explicit retriable failover event, per model")


def param_cache_total():
    return REGISTRY.counter(
        "kfserving_tpu_param_cache_total",
        "mmap param-cache lookups and stores, by outcome "
        "(hit|miss|store|error)")


# -- device-discipline sanitizer (KFS_SANITIZE=1) ----------------------
def sanitizer_violations_total():
    return REGISTRY.counter(
        "kfserving_tpu_sanitizer_violations_total",
        "Runtime device-discipline violations by kind "
        "(forbidden_transfer: implicit host<->device transfer under "
        "the armed guard; recompile: a compilation after a source's "
        "declared warmup; loop_stall: the event loop failed to run a "
        "watchdog tick within the threshold)")


def sanitizer_armed():
    return REGISTRY.gauge(
        "kfserving_tpu_sanitizer_armed",
        "1 while KFS_SANITIZE=1 has the runtime sanitizer active in "
        "this process (transfer guard + recompile assertion + loop "
        "watchdog)")


# -- incident engine (automated cross-signal diagnosis) -----------------
def incident_open():
    return REGISTRY.gauge(
        "kfserving_tpu_incident_open",
        "Open (undiagnosed-recovery) incidents per dedup key — the "
        "model under breach, or `_server` for process-wide storms")


def incident_opened_total():
    return REGISTRY.counter(
        "kfserving_tpu_incident_opened_total",
        "Incidents opened, labeled by the causal classifier's "
        "top-ranked hypothesis at open time (queue_wait|"
        "device_compute|cache_miss_storm|eviction_thrash|"
        "recompile_host_sync|brownout_shed|failover|unclassified)")


def incident_triggers_total():
    return REGISTRY.counter(
        "kfserving_tpu_incident_triggers_total",
        "Detector firings fed to the incident engine by trigger kind "
        "(slo_breach|trend|sanitizer|eviction_storm|faultback_storm|"
        "failover) — each either opens an incident or attaches to the "
        "open one inside the dedup window")


def incident_failures_total():
    return REGISTRY.counter(
        "kfserving_tpu_incident_failures_total",
        "Incident pipeline failures by reason (error = diagnosis "
        "raised and was swallowed, dropped = the bounded trigger "
        "queue overflowed while the worker was wedged) — under chaos "
        "the pipeline degrades to plain detector pins, it never "
        "blocks serving")


# An incident's life spans seconds (a one-tick blip) to tens of
# minutes (a sustained regression) — the request-latency ladder is
# three decades too low.
INCIDENT_DURATION_BUCKETS_MS = [
    1000, 5000, 15000, 60000, 300000, 900000, 3600000]


def incident_duration_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_incident_duration_ms",
        "Open-to-close wall time of resolved incidents (close = "
        "recovery observed, then the cooldown window passed with no "
        "further triggers)",
        buckets=INCIDENT_DURATION_BUCKETS_MS)


# -- speculative decoding (GenerationEngine draft/verify waves) ---------
def specdec_proposed_tokens_total():
    return REGISTRY.counter(
        "kfserving_tpu_specdec_proposed_tokens_total",
        "Draft tokens proposed to the verify dispatch, per model and "
        "proposer (draft = registered draft model, ngram = the "
        "prompt-lookup head)")


def specdec_accepted_tokens_total():
    return REGISTRY.counter(
        "kfserving_tpu_specdec_accepted_tokens_total",
        "Proposed draft tokens the target's own sampled draw agreed "
        "with (the longest-agreeing-prefix rule), per model and "
        "proposer — accepted/proposed is the acceptance rate")


def specdec_fallbacks_total():
    return REGISTRY.counter(
        "kfserving_tpu_specdec_fallbacks_total",
        "Speculative waves degraded to plain non-speculative decode "
        "by an injected fault, per model and seam (site=draft|"
        "verify) — output stays bit-exact, only tokens-per-dispatch "
        "drops")


# Accepted length per spec wave row is 1 (first draft token rejected;
# the target's own draw still lands) up to K+1 (all K accepted + the
# bonus draw) — a short linear-ish ladder, not the token-count decades.
SPECDEC_LENGTH_BUCKETS = [1, 2, 3, 4, 6, 8, 12, 16]


def specdec_accepted_length_tokens():
    return REGISTRY.histogram(
        "kfserving_tpu_specdec_accepted_length_tokens",
        "Tokens committed per live slot per speculative wave "
        "(1 = proposal rejected outright, K+1 = fully accepted plus "
        "the bonus draw), per model",
        buckets=SPECDEC_LENGTH_BUCKETS)


def specdec_draft_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_specdec_draft_ms",
        "Draft-proposal overhead per speculative wave (device time "
        "for a registered draft model, host time for the n-gram "
        "head), per model and proposer",
        buckets=LATENCY_BUCKETS_MS)


def specdec_acceptance_ratio():
    return REGISTRY.gauge(
        "kfserving_tpu_specdec_acceptance_ratio",
        "Running acceptance rate (accepted/proposed draft tokens, "
        "0..1) per model — the knob that decides whether K is paying "
        "for itself")
