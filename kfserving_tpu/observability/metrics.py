"""The metric catalog: accessors for every cross-layer instrument.

Each accessor re-resolves its family from the process registry on
every call (registration is an idempotent dict lookup), so a
test-time `REGISTRY.reset()` can never leave a caller holding a stale
instrument.  Layers call e.g.::

    from kfserving_tpu.observability import metrics as obs

    obs.batch_queue_wait_ms().labels(bucket=str(key)).observe(age_ms)
    obs.llm_ttft_ms().observe(ttft, trace_id=req.trace_id)

Series naming follows the seed's `kfserving_tpu_` prefix; histograms
are milliseconds unless the name says otherwise.
"""

from kfserving_tpu.observability.registry import (
    LATENCY_BUCKETS_MS,
    RATIO_BUCKETS,
    REGISTRY,
    THROUGHPUT_BUCKETS,
)


# -- batcher ------------------------------------------------------------
def batch_queue_wait_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_batch_queue_wait_ms",
        "Time a request's oldest instance waited in the dynamic "
        "batcher queue before its batch flushed")


def batch_fill_ratio():
    return REGISTRY.histogram(
        "kfserving_tpu_batch_fill_ratio",
        "Flushed batch size as a fraction of the executed bucket "
        "(1.0 = zero pad slots)", buckets=RATIO_BUCKETS)


# -- engine -------------------------------------------------------------
def engine_stage_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_engine_stage_ms",
        "Per-execution engine stage timing (stage=prepare|transfer|"
        "compute|fetch)")


def compile_cache_events():
    return REGISTRY.counter(
        "kfserving_tpu_compile_cache_total",
        "Compiled-executable cache lookups by outcome (outcome=hit "
        "means the shape was already compiled; miss paid a compile)")


# -- LLM generation -----------------------------------------------------
def llm_ttft_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_llm_ttft_ms",
        "Time from generation submit to the first emitted token")


def llm_inter_token_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_llm_inter_token_ms",
        "Gap between consecutive emitted tokens of one generation")


def llm_tokens_per_second():
    return REGISTRY.histogram(
        "kfserving_tpu_llm_tokens_per_second",
        "Whole-generation decode throughput at finish",
        buckets=THROUGHPUT_BUCKETS)


def llm_tokens_total():
    return REGISTRY.counter(
        "kfserving_tpu_llm_tokens_total",
        "Prompt and generated tokens by direction (direction=in|out)")


# -- reliability --------------------------------------------------------
def breaker_state():
    return REGISTRY.gauge(
        "kfserving_tpu_breaker_state",
        "Circuit breaker state (0=closed, 1=half_open, 2=open)")


def breaker_transitions():
    return REGISTRY.counter(
        "kfserving_tpu_breaker_transitions_total",
        "Circuit breaker state transitions (to=open|closed)")


def retry_total():
    return REGISTRY.counter(
        "kfserving_tpu_retry_total",
        "Retries performed, labeled by edge (policy name) and reason "
        "(exception class)")


def deadline_exceeded_total():
    return REGISTRY.counter(
        "kfserving_tpu_deadline_exceeded_total",
        "Requests shed because their latency budget ran out, by stage")


# -- ingress router -----------------------------------------------------
def router_inflight():
    return REGISTRY.gauge(
        "kfserving_tpu_router_inflight",
        "In-flight proxied requests per component")


def router_requests_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_requests_total",
        "Requests routed per component")


def router_rotation_skips_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_rotation_skips_total",
        "Replica picks skipped because the host's breaker was open")


def router_shed_total():
    return REGISTRY.counter(
        "kfserving_tpu_router_shed_total",
        "Requests the router shed instead of proxying, by reason")


def router_request_ms():
    return REGISTRY.histogram(
        "kfserving_tpu_router_request_ms",
        "Router-observed request latency (proxy hop included)",
        buckets=LATENCY_BUCKETS_MS)
