"""Env-knob parsing shared by the monitoring family (KFS_MONITOR_*,
KFS_SLO_*, KFS_FLIGHTRECORDER_*).  Lenient like the reliability
knobs: a non-numeric value logs once and falls back to the default —
a typo'd knob must degrade to defaults, never crash the server."""

import logging
import os

logger = logging.getLogger("kfserving_tpu.monitoring")


def env_number(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default
