"""Online drift / outlier monitors: streaming consumers for the bus.

PR 0 shipped the detectors as *served models* — a payload logger
mirrors traffic to their `:predict` route over HTTP (the alibi-detect
deployment shape).  These monitors wrap the same math
(`detectors/drift.py` KS tests, `detectors/outlier.py` Mahalanobis
scoring) as in-process MonitorBus consumers: no mirror hop, no second
service, and the verdicts land in the metrics registry as per-model
series instead of response bodies nobody scrapes —

    kfserving_tpu_drift_score{model=...}
    kfserving_tpu_outlier_rate{model=...}
    kfserving_tpu_monitor_alert_state{model=..., monitor=...}

Both monitors keep windowed reference stats: the reference sample is
summarized once at construction (sorted columns for KS, fitted
mean/precision for Mahalanobis) and the live side is a bounded sliding
window, so per-event work is O(window) worst case and re-tests run at
a stride, exactly like the offline detectors.
"""

import json
import logging
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

logger = logging.getLogger("kfserving_tpu.monitoring.monitors")


def event_instances(event: Dict[str, Any]) -> Optional[np.ndarray]:
    """[n, d] float array from a bus event's payload, or None when the
    payload is not a numeric V1 body (generate bodies, V2 tensors,
    malformed JSON — the monitor just skips those samples)."""
    payload = event.get("payload")
    if not payload:
        return None
    try:
        body = json.loads(payload)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(body, dict):
        return None
    instances = body.get("instances", body.get("inputs"))
    if not isinstance(instances, list) or not instances:
        return None
    try:
        arr = np.asarray(instances, np.float64)
    except (ValueError, TypeError):
        return None
    if arr.dtype == object:
        return None
    if arr.ndim == 1:
        arr = arr[None]
    return arr.reshape(len(arr), -1)


class _ModelFilter:
    """Shared event gating: a monitor watches exactly one model."""

    def __init__(self, model: str):
        self.model = model

    def _instances(self, event: Dict[str, Any]
                   ) -> Optional[np.ndarray]:
        if event.get("model") != self.model:
            return None
        return event_instances(event)


class DriftMonitor(_ModelFilter):
    """Sliding-window per-feature KS drift vs a reference sample,
    Bonferroni-corrected — `detectors/drift.py` semantics as a
    streaming consumer."""

    def __init__(self, model: str, reference: np.ndarray,
                 window: int = 128, p_value: float = 0.05,
                 test_stride: Optional[int] = None):
        super().__init__(model)
        self.name = f"drift:{model}"
        reference = np.asarray(reference, np.float64)
        if reference.ndim != 2 or len(reference) < 2:
            raise ValueError("drift reference must be [m>=2, d]")
        self.reference_len = len(reference)
        self._ref_sorted = np.sort(reference, axis=0)
        self.dim = reference.shape[1]
        self.window_size = max(1, int(window))
        self.p_value = float(p_value)
        self.window: deque = deque(maxlen=self.window_size)
        self.test_stride = int(test_stride if test_stride is not None
                               else max(1, self.window_size // 16))
        self._rows_since_test = 0
        self.alerting = False
        self.last_result: Optional[Dict[str, Any]] = None

    @classmethod
    def from_detector(cls, detector, window: Optional[int] = None
                      ) -> "DriftMonitor":
        """Wrap a loaded `KSDriftDetector` (reuse its downloaded
        reference and config) as a streaming monitor."""
        return cls(detector.name, detector.reference,
                   window=window or detector.window_size,
                   p_value=detector.p_value,
                   test_stride=detector.test_stride)

    async def __call__(self, event: Dict[str, Any]) -> None:
        from kfserving_tpu.detectors.drift import ks_drift_test
        from kfserving_tpu.observability import metrics as obs

        instances = self._instances(event)
        if instances is None or instances.shape[1] != self.dim:
            return
        for row in instances:
            self.window.append(row)
        self._rows_since_test += len(instances)
        if len(self.window) < self.window_size or \
                self._rows_since_test < self.test_stride:
            return
        self._rows_since_test = 0
        result = ks_drift_test(self._ref_sorted, np.stack(self.window),
                               self.reference_len, self.p_value)
        self.alerting = result["drift"]
        self.last_result = {
            "drift": self.alerting,
            "score": round(result["score"], 6),
            "min_p_value": round(min(result["p_values"]), 8),
            "threshold": result["threshold"],
            "window": result["window"],
        }
        obs.drift_score().labels(model=self.model).set(
            result["score"])
        obs.monitor_alert_state().labels(
            model=self.model, monitor="drift").set(
                1.0 if self.alerting else 0.0)
        if self.alerting:
            logger.warning("drift alert for model %s: %s", self.model,
                           self.last_result)


class OutlierMonitor(_ModelFilter):
    """Windowed Mahalanobis outlier RATE — `detectors/outlier.py`
    scoring as a streaming consumer.  The exported signal is the
    fraction of the sliding window past the fitted threshold, which a
    single extreme request can't saturate (per-request verdicts stay
    the served detector's job)."""

    def __init__(self, model: str, reference: Optional[np.ndarray] = None,
                 scorer=None, threshold: Optional[float] = None,
                 threshold_percentile: float = 99.5,
                 window: int = 128, alert_rate: float = 0.1):
        super().__init__(model)
        self.name = f"outlier:{model}"
        if scorer is None:
            from kfserving_tpu.detectors.outlier import MahalanobisScorer

            if reference is None:
                raise ValueError(
                    "OutlierMonitor needs a reference sample or a "
                    "fitted scorer")
            scorer = MahalanobisScorer(reference)
        self.scorer = scorer
        if threshold is None:
            from kfserving_tpu.detectors.outlier import fit_threshold

            if reference is None:
                raise ValueError(
                    "threshold required when wrapping a bare scorer")
            threshold = fit_threshold(self.scorer, reference,
                                      threshold_percentile)
        self.threshold = float(threshold)
        self.window_size = max(1, int(window))
        self.alert_rate = float(alert_rate)
        self.flags: deque = deque(maxlen=self.window_size)
        self.seen = 0
        self.flagged = 0
        self.alerting = False

    @classmethod
    def from_detector(cls, detector, window: int = 128,
                      alert_rate: float = 0.1) -> "OutlierMonitor":
        """Wrap a loaded `OutlierDetector` (reuse its fitted scorer
        and threshold) as a streaming monitor."""
        return cls(detector.name, scorer=detector.scorer,
                   threshold=detector.threshold, window=window,
                   alert_rate=alert_rate)

    async def __call__(self, event: Dict[str, Any]) -> None:
        from kfserving_tpu.observability import metrics as obs
        from kfserving_tpu.protocol.errors import InvalidInput

        instances = self._instances(event)
        if instances is None:
            return
        try:
            scores = self.scorer.score(instances)
        except InvalidInput:
            return  # dimension mismatch: not this monitor's traffic
        flags = scores > self.threshold
        self.seen += len(flags)
        self.flagged += int(flags.sum())
        self.flags.extend(bool(f) for f in flags)
        rate = (sum(self.flags) / len(self.flags)) if self.flags else 0.0
        was = self.alerting
        self.alerting = len(self.flags) >= min(8, self.window_size) \
            and rate >= self.alert_rate
        obs.outlier_rate().labels(model=self.model).set(rate)
        obs.monitor_alert_state().labels(
            model=self.model, monitor="outlier").set(
                1.0 if self.alerting else 0.0)
        if self.alerting and not was:
            logger.warning(
                "outlier alert for model %s: window rate %.3f >= %.3f "
                "(threshold %.3f)", self.model, rate, self.alert_rate,
                self.threshold)
