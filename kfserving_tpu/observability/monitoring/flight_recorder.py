"""Flight recorder: a per-replica ring buffer of request timelines.

When an SLO pages, the first question is "show me one bad request" —
and by then the interesting requests have usually rotated out of every
log.  The recorder keeps the last N request timelines (trace id, stage
timings from server/dataplane/batcher/engine/generator spans, batch
fill, outcome) in a bounded ring, and PINS entries that tripped a
trigger into a separate bounded buffer so evidence survives the flood
of healthy traffic that follows an incident:

    slo_breach       the model's SLO alert state was active
    slo_violation    latency exceeded the model's declared objective
    deadline_shed    the request died of its budget (504)
    error            5xx outcome
    latency_outlier  latency above the rolling per-model p99

Dumpable at `GET /debug/flightrecorder` (federated through the router
like `/debug/traces`).  Knobs: `KFS_FLIGHTRECORDER_SIZE` (ring),
`KFS_FLIGHTRECORDER_PINNED` (pin buffer),
`KFS_FLIGHTRECORDER_LATENCY_WINDOW` (p99 sample window).
"""

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("kfserving_tpu.monitoring.flightrecorder")

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.monitoring.knobs import env_number

DEFAULT_SIZE = 256
DEFAULT_PINNED = 64
DEFAULT_LATENCY_WINDOW = 256
# Below this many samples the rolling p99 is noise, not a trigger.
MIN_OUTLIER_SAMPLES = 32


class FlightRecorder:
    def __init__(self, size: int = DEFAULT_SIZE,
                 pinned_size: int = DEFAULT_PINNED,
                 latency_window: int = DEFAULT_LATENCY_WINDOW):
        self.size = max(1, int(size))
        self.pinned_size = max(1, int(pinned_size))
        self.latency_window = max(MIN_OUTLIER_SAMPLES,
                                  int(latency_window))
        self._ring: deque = deque(maxlen=self.size)
        self._pinned: deque = deque(maxlen=self.pinned_size)
        self._latencies: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self.recorded = 0
        self.pinned_count = 0
        # Pin taps: called with every PINNED entry, outside the lock
        # (recording happens on the event loop, executor threads, and
        # the sanitizer watchdog thread alike — listeners must be
        # thread-safe and cheap).  The incident engine subscribes here
        # to turn detector pins into incident triggers.
        self._pin_listeners: List[Callable[[Dict[str, Any]], None]] = []

    @classmethod
    def from_env(cls) -> "FlightRecorder":
        return cls(
            size=int(env_number("KFS_FLIGHTRECORDER_SIZE",
                                DEFAULT_SIZE)),
            pinned_size=int(env_number("KFS_FLIGHTRECORDER_PINNED",
                                       DEFAULT_PINNED)),
            latency_window=int(env_number(
                "KFS_FLIGHTRECORDER_LATENCY_WINDOW",
                DEFAULT_LATENCY_WINDOW)))

    # -- triggers ----------------------------------------------------------
    def observe_latency(self, model: str, latency_ms: float) -> bool:
        """Feed the per-model rolling latency window; True when this
        observation sits above the window's p99 (the latency-outlier
        pin trigger).  The window is consulted BEFORE this sample
        joins it, so one giant outlier can't raise the bar against
        itself."""
        with self._lock:
            window = self._latencies.get(model)
            if window is None:
                window = self._latencies[model] = deque(
                    maxlen=self.latency_window)
            is_outlier = False
            if len(window) >= MIN_OUTLIER_SAMPLES:
                ordered = sorted(window)
                p99 = ordered[min(len(ordered) - 1,
                                  int(len(ordered) * 0.99))]
                is_outlier = latency_ms > p99
            window.append(latency_ms)
            return is_outlier

    # -- recording ---------------------------------------------------------
    def record(self, entry: Dict[str, Any],
               pin: Optional[str] = None) -> None:
        """Append one request timeline; `pin` names the trigger that
        also copies it into the pinned buffer."""
        entry = dict(entry)
        entry.setdefault("ts", time.time())
        if pin:
            entry["pinned"] = pin
        with self._lock:
            self.recorded += 1
            self._ring.append(entry)
            if pin:
                self.pinned_count += 1
                self._pinned.append(entry)
        if pin:
            obs.flightrecorder_pinned_total().labels(reason=pin).inc()
            for listener in list(self._pin_listeners):
                try:
                    listener(entry)
                except Exception:
                    # A broken tap must never fail the recording path.
                    logger.exception("pin listener failed")

    def add_pin_listener(
            self, listener: Callable[[Dict[str, Any]], None]) -> None:
        """Subscribe to pinned entries (each call gets the stamped
        entry dict, `pinned` key included)."""
        self._pin_listeners.append(listener)

    def remove_pin_listener(
            self, listener: Callable[[Dict[str, Any]], None]) -> None:
        try:
            self._pin_listeners.remove(listener)
        except ValueError:
            pass

    # -- dumping -----------------------------------------------------------
    def dump(self, limit: int = 100,
             pinned_only: bool = False,
             pin_type: Optional[str] = None,
             since_ts: Optional[float] = None) -> Dict[str, Any]:
        """`pin_type` keeps only entries whose pin reason starts with
        the given prefix (`trend`, `slo_`, `sanitizer_recompile`, ...)
        — unpinned ring entries are excluded too, so an incident
        bundle can pull just the detector evidence instead of the
        whole ring.  `since_ts` keeps entries stamped at or after the
        given wall-clock time."""
        # Clamp BEFORE slicing: [-0:] is the whole deque, and a
        # negative limit would slice an arbitrary tail — a ?limit=0
        # query must mean "none", not "everything".
        limit = max(0, int(limit))

        def keep(entry: Dict[str, Any]) -> bool:
            if since_ts is not None and \
                    float(entry.get("ts") or 0.0) < since_ts:
                return False
            if pin_type:
                reason = entry.get("pinned")
                if not reason or not str(reason).startswith(pin_type):
                    return False
            return True

        filtering = pin_type or since_ts is not None
        with self._lock:
            pinned_src = ([e for e in self._pinned if keep(e)]
                          if filtering else list(self._pinned))
            pinned = pinned_src[-limit:] if limit else []
            if pinned_only or not limit:
                entries = []
            else:
                ring_src = ([e for e in self._ring if keep(e)]
                            if filtering else list(self._ring))
                entries = ring_src[-limit:]
            return {
                "recorded": self.recorded,
                "pinned_total": self.pinned_count,
                "entries": entries,
                "pinned": pinned,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pinned.clear()
            self._latencies.clear()
