"""Flight recorder: a per-replica ring buffer of request timelines.

When an SLO pages, the first question is "show me one bad request" —
and by then the interesting requests have usually rotated out of every
log.  The recorder keeps the last N request timelines (trace id, stage
timings from server/dataplane/batcher/engine/generator spans, batch
fill, outcome) in a bounded ring, and PINS entries that tripped a
trigger into a separate bounded buffer so evidence survives the flood
of healthy traffic that follows an incident:

    slo_breach       the model's SLO alert state was active
    slo_violation    latency exceeded the model's declared objective
    deadline_shed    the request died of its budget (504)
    error            5xx outcome
    latency_outlier  latency above the rolling per-model p99

Dumpable at `GET /debug/flightrecorder` (federated through the router
like `/debug/traces`).  Knobs: `KFS_FLIGHTRECORDER_SIZE` (ring),
`KFS_FLIGHTRECORDER_PINNED` (pin buffer),
`KFS_FLIGHTRECORDER_LATENCY_WINDOW` (p99 sample window).
"""

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.monitoring.knobs import env_number

DEFAULT_SIZE = 256
DEFAULT_PINNED = 64
DEFAULT_LATENCY_WINDOW = 256
# Below this many samples the rolling p99 is noise, not a trigger.
MIN_OUTLIER_SAMPLES = 32


class FlightRecorder:
    def __init__(self, size: int = DEFAULT_SIZE,
                 pinned_size: int = DEFAULT_PINNED,
                 latency_window: int = DEFAULT_LATENCY_WINDOW):
        self.size = max(1, int(size))
        self.pinned_size = max(1, int(pinned_size))
        self.latency_window = max(MIN_OUTLIER_SAMPLES,
                                  int(latency_window))
        self._ring: deque = deque(maxlen=self.size)
        self._pinned: deque = deque(maxlen=self.pinned_size)
        self._latencies: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self.recorded = 0
        self.pinned_count = 0

    @classmethod
    def from_env(cls) -> "FlightRecorder":
        return cls(
            size=int(env_number("KFS_FLIGHTRECORDER_SIZE",
                                DEFAULT_SIZE)),
            pinned_size=int(env_number("KFS_FLIGHTRECORDER_PINNED",
                                       DEFAULT_PINNED)),
            latency_window=int(env_number(
                "KFS_FLIGHTRECORDER_LATENCY_WINDOW",
                DEFAULT_LATENCY_WINDOW)))

    # -- triggers ----------------------------------------------------------
    def observe_latency(self, model: str, latency_ms: float) -> bool:
        """Feed the per-model rolling latency window; True when this
        observation sits above the window's p99 (the latency-outlier
        pin trigger).  The window is consulted BEFORE this sample
        joins it, so one giant outlier can't raise the bar against
        itself."""
        with self._lock:
            window = self._latencies.get(model)
            if window is None:
                window = self._latencies[model] = deque(
                    maxlen=self.latency_window)
            is_outlier = False
            if len(window) >= MIN_OUTLIER_SAMPLES:
                ordered = sorted(window)
                p99 = ordered[min(len(ordered) - 1,
                                  int(len(ordered) * 0.99))]
                is_outlier = latency_ms > p99
            window.append(latency_ms)
            return is_outlier

    # -- recording ---------------------------------------------------------
    def record(self, entry: Dict[str, Any],
               pin: Optional[str] = None) -> None:
        """Append one request timeline; `pin` names the trigger that
        also copies it into the pinned buffer."""
        entry = dict(entry)
        entry.setdefault("ts", time.time())
        if pin:
            entry["pinned"] = pin
        with self._lock:
            self.recorded += 1
            self._ring.append(entry)
            if pin:
                self.pinned_count += 1
                self._pinned.append(entry)
        if pin:
            obs.flightrecorder_pinned_total().labels(reason=pin).inc()

    # -- dumping -----------------------------------------------------------
    def dump(self, limit: int = 100,
             pinned_only: bool = False) -> Dict[str, Any]:
        # Clamp BEFORE slicing: [-0:] is the whole deque, and a
        # negative limit would slice an arbitrary tail — a ?limit=0
        # query must mean "none", not "everything".
        limit = max(0, int(limit))
        with self._lock:
            pinned = list(self._pinned)[-limit:] if limit else []
            entries = ([] if pinned_only or not limit
                       else list(self._ring)[-limit:])
            return {
                "recorded": self.recorded,
                "pinned_total": self.pinned_count,
                "entries": entries,
                "pinned": pinned,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pinned.clear()
            self._latencies.clear()
