"""Online model monitoring: the loop that ACTS on PR 1/2's telemetry.

PR 2 made the stack observable (traces, stage metrics, federation) but
nothing in-process consumed any of it.  This package closes the loop,
per InferLine's continuous-evaluation discipline and
TensorFlow-Serving's built-in (not bolted-on) model-health stance:

- `bus`             — bounded, sampling, never-blocking request tee
                      feeding async consumers (the in-process
                      equivalent of the CloudEvents logger hop);
- `monitors`        — streaming drift / outlier monitors wrapping the
                      offline detectors, exporting per-model score /
                      rate / alert-state series;
- `slo`             — per-model latency/error objectives evaluated as
                      multi-window burn rates over the PR-2 request
                      series, served at `GET /v2/health/slo`;
- `flight_recorder` — ring buffer of recent request timelines that
                      auto-pins SLO breaches, deadline sheds, and
                      latency outliers, at `GET /debug/flightrecorder`.

`Monitoring` is the per-server facade the ModelServer owns: it wires
the bus onto the request-hook point, runs the SLO evaluation loop as a
server service, and assembles flight-recorder entries (stage timings +
tracer spans) on every request completion.

Import discipline (observability package contract): nothing from
`server/`, `control/`, `engine/`, or `reliability/` — the server hands
itself in and the monitors import detector math lazily.
"""

import asyncio
import logging
from typing import Any, Dict, List, Optional

from kfserving_tpu.observability.monitoring.bus import MonitorBus
from kfserving_tpu.observability.monitoring.flight_recorder import (
    FlightRecorder,
)
from kfserving_tpu.observability.monitoring.knobs import env_number
from kfserving_tpu.observability.monitoring.monitors import (
    DriftMonitor,
    OutlierMonitor,
)
from kfserving_tpu.observability.monitoring.slo import (
    DEFAULT_EVAL_S,
    ENV_EVAL,
    SLOEngine,
    SLOObjective,
)

logger = logging.getLogger("kfserving_tpu.monitoring")

__all__ = [
    "MonitorBus", "FlightRecorder", "DriftMonitor", "OutlierMonitor",
    "SLOEngine", "SLOObjective", "Monitoring",
]

# Span names whose timings make up a request's flight-recorder
# timeline (the cross-layer stages PR 2 instrumented).
_TIMELINE_SPAN_PREFIXES = ("server.", "dataplane.", "batcher.",
                           "engine.", "generator.")


class Monitoring:
    """Per-ModelServer monitoring loop: bus + monitors + SLO engine +
    flight recorder.  Constructed with the server (cheap — no tasks);
    `start()`/`stop()` run as one of the server's background
    services."""

    def __init__(self, server):
        self.server = server
        self.bus = MonitorBus.from_env()
        self.bus.attach(server)
        self.flight_recorder = FlightRecorder.from_env()
        # The server's private request registry: both HTTP and gRPC
        # requests land there (PR 2 routed gRPC through
        # Metrics.observe_request), so the SLO sees every protocol.
        self.slo = SLOEngine.from_env([server.metrics.registry])
        self.eval_interval_s = env_number(ENV_EVAL, DEFAULT_EVAL_S)
        self._slo_task: Optional[asyncio.Task] = None

    # -- service lifecycle -------------------------------------------------
    async def start(self) -> None:
        await self.bus.start()
        if self.slo.enabled and self._slo_task is None:
            self.slo.tick()  # baseline snapshot at serving start
            self._slo_task = asyncio.get_running_loop().create_task(
                self._slo_loop())

    async def stop(self) -> None:
        if self._slo_task is not None:
            self._slo_task.cancel()
            try:
                await self._slo_task
            except asyncio.CancelledError:
                pass
            self._slo_task = None
        await self.bus.stop()

    async def _slo_loop(self) -> None:
        while True:
            await asyncio.sleep(max(0.05, self.eval_interval_s))
            try:
                self.slo.tick()
            except Exception:  # evaluation must never kill the loop
                logger.exception("SLO evaluation failed")

    # -- monitor wiring ----------------------------------------------------
    def add_drift_monitor(self, monitor: DriftMonitor) -> DriftMonitor:
        self.bus.subscribe(monitor)
        return monitor

    def add_outlier_monitor(self, monitor: OutlierMonitor
                            ) -> OutlierMonitor:
        self.bus.subscribe(monitor)
        return monitor

    # -- flight recording --------------------------------------------------
    def record_request(self, model: str, verb: str, status: int,
                       latency_ms: float,
                       trace_id: Optional[str] = None,
                       stages: Optional[Dict[str, float]] = None
                       ) -> None:
        """Assemble and record one request's timeline; evaluates the
        pin triggers.  Called from the server's completion path —
        must never raise into it."""
        try:
            pin = self._pin_reason(model, status, latency_ms)
            is_outlier = self.flight_recorder.observe_latency(
                model, latency_ms)
            if pin is None and is_outlier:
                pin = "latency_outlier"
            entry = {
                "trace_id": trace_id,
                "model": model,
                "verb": verb,
                "status": status,
                "latency_ms": round(latency_ms, 3),
                "stages": stages or {},
            }
            # Cost attribution (ISSUE 13): the request's cost record
            # (attributed device ms, prefill/decode tokens, blocks
            # held, cache-saved tokens) rides every entry it exists
            # for — a pinned p99 outlier then shows what the request
            # COST, not just how long it took.  One bounded-dict
            # lookup; absent for non-generative verbs.
            if trace_id:
                from kfserving_tpu.observability import attribution

                cost = attribution.lookup(trace_id)
                if cost is not None:
                    entry["cost"] = cost
            # Eager span capture ONLY for pinned entries: pinned
            # evidence must not depend on the tracer ring still
            # holding the spans at dump time, but scanning the ring
            # for every healthy request would put an O(ring) copy +
            # tracer-lock hit on the serving hot path.  Un-pinned
            # ring entries resolve their timeline lazily at dump
            # (best-effort — spans may have rotated out).
            if pin:
                entry["timeline"] = self._timeline(trace_id)
                # Device-path evidence (ISSUE 6): the engine events
                # overlapping this request's span — a p99-outlier pin
                # shows WHICH decode wave / prefill chunk / preemption
                # / HOLD window produced the tail, not just that the
                # engine stage was slow.
                entry["engine_events"] = self._engine_events(
                    latency_ms)
            self.flight_recorder.record(entry, pin=pin)
        except Exception:
            logger.exception("flight-recorder capture failed")

    def dump_flightrecorder(self, limit: int = 100,
                            pinned_only: bool = False,
                            pin_type: Optional[str] = None,
                            since_ts: Optional[float] = None
                            ) -> Dict[str, Any]:
        """The /debug/flightrecorder body: recorder dump with lazy
        timeline resolution for ring entries recorded without one (a
        debug endpoint can afford the tracer scans the hot path
        can't).  `pin_type`/`since_ts` pass through to the recorder's
        pin-stream filters (ISSUE 18)."""
        dump = self.flight_recorder.dump(limit=limit,
                                         pinned_only=pinned_only,
                                         pin_type=pin_type,
                                         since_ts=since_ts)
        # Copies, not in-place writes: dump() hands back the stored
        # dicts, which the recording path may be appending around.
        dump["entries"] = [
            entry if "timeline" in entry
            else dict(entry,
                      timeline=self._timeline(entry.get("trace_id")))
            for entry in dump["entries"]]
        return dump

    def _pin_reason(self, model: str, status: int,
                    latency_ms: float) -> Optional[str]:
        if status == 504:
            return "deadline_shed"
        if status == 503:
            # Admission-queue overflow / model not ready: capacity
            # evidence, distinct from a 5xx failure.
            return "unavailable"
        if status >= 500:
            return "error"
        objective = self.slo.objective_for(model)
        if objective is not None and objective.latency_ms is not None \
                and latency_ms > objective.latency_ms:
            return ("slo_breach" if self.slo.alerting(model)
                    else "slo_violation")
        return None

    @staticmethod
    def _engine_events(latency_ms: float,
                       limit: int = 64) -> List[Dict[str, Any]]:
        """Engine timeline events overlapping the just-finished
        request's wall-clock span (+50 ms of slack on the open end:
        the pin evaluates microseconds after the request closed, and
        the wave that delivered its last token may be stamped a hair
        later)."""
        import time as _time

        from kfserving_tpu.observability.profiling import TIMELINE

        now = _time.time()
        return TIMELINE.window(now - latency_ms / 1000.0 - 0.05,
                               now + 0.05, limit=limit)

    @staticmethod
    def _timeline(trace_id: Optional[str]) -> List[Dict[str, Any]]:
        """The request's stage spans (batcher queue wait, engine
        prepare/transfer/compute/fetch with batch fill, generator
        decode, dataplane stages), captured NOW — pinned evidence must
        not depend on the tracer ring still holding the spans at dump
        time."""
        if not trace_id:
            return []
        from kfserving_tpu.tracing import tracer

        return [s for s in tracer.spans(trace_id, limit=64)
                if s["name"].startswith(_TIMELINE_SPAN_PREFIXES)]
