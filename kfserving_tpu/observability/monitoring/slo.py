"""SLO engine: multi-window error-budget burn rates over live series.

InferLine's premise (arXiv:1812.01776) is that a serving system must
*continuously* evaluate tight latency objectives against live traffic
— not dashboards after the fact.  PR 2 already measures everything
needed (the `kfserving_tpu_request_total` counter and the
`kfserving_tpu_request_latency_ms` histogram, per model); this engine
closes the loop in-process:

- objectives are declared per model (`KFS_SLO_OBJECTIVES` JSON, or a
  `KFS_SLO_DEFAULT_*` wildcard applied to every served model):
  a latency bound + availability target ("99% of requests under
  100ms") and/or an error-rate target ("99.9% non-5xx");
- evaluation takes periodic snapshots of the cumulative series and
  computes the burn rate over each configured window: the fraction of
  the error budget (1 - target) being spent, where 1.0 means spending
  exactly the budget and N means exhausting it N times faster;
- the multi-window rule (the SRE-workbook shape): a model alerts only
  when EVERY window burns past the threshold — the short window gives
  fast detection, the long window keeps a single spike from paging.

Latency thresholds are evaluated against histogram buckets, so a
threshold between bucket bounds rounds DOWN to the nearest bound
(conservative: requests between the bound and the threshold count as
bad).  Declare objectives on bucket boundaries
(`LATENCY_BUCKETS_MS`) for exact accounting.

State is all derived from cumulative counters, so the engine is
restart-safe and costs nothing between ticks.
"""

import bisect
import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.metrics import (
    REQUEST_LATENCY_SERIES,
    REQUEST_TOTAL_SERIES,
)

logger = logging.getLogger("kfserving_tpu.monitoring.slo")

ENV_OBJECTIVES = "KFS_SLO_OBJECTIVES"
ENV_DEFAULT_LATENCY = "KFS_SLO_DEFAULT_LATENCY_MS"
ENV_DEFAULT_TARGET = "KFS_SLO_DEFAULT_TARGET"
ENV_WINDOWS = "KFS_SLO_WINDOWS_S"
ENV_BURN_ALERT = "KFS_SLO_BURN_ALERT"
ENV_EVAL = "KFS_SLO_EVAL_S"

DEFAULT_TARGET = 0.99
DEFAULT_WINDOWS_S = (60.0, 300.0)
DEFAULT_BURN_ALERT = 2.0
DEFAULT_EVAL_S = 5.0
# Hard cap on retained snapshots: the background loop's cadence keeps
# history small by itself, but ?refresh=1 lets an unauthenticated
# poller force a tick per request — memory and tick cost must stay
# bounded regardless (past the cap the oldest snapshots drop, which
# can only SHORTEN the effective long window, never break it).
MAX_SNAPSHOTS = 256

def _window_label(window: float) -> str:
    """Exposition/report label for a window: integral seconds render
    bare ("60"), fractional ones keep their fraction ("0.5") — two
    sub-second windows must not collide into one label."""
    return str(int(window)) if window == int(window) else str(window)


def _clamp_target(target: float) -> float:
    """Targets must leave a non-empty budget: 1.0 (or more) would make
    every burn rate infinite.  Clamp into (0, 1) loudly."""
    if not 0.0 < target < 1.0:
        logger.warning("SLO target %s outside (0, 1); clamping", target)
        return min(0.9999, max(0.0001, target))
    return target


@dataclass
class SLOObjective:
    model: str
    latency_ms: Optional[float] = None
    target: float = DEFAULT_TARGET          # for the latency objective
    error_target: Optional[float] = None    # non-5xx availability

    def __post_init__(self):
        self.target = _clamp_target(float(self.target))
        if self.error_target is not None:
            self.error_target = _clamp_target(float(self.error_target))
        if self.latency_ms is not None:
            self.latency_ms = float(self.latency_ms)

    def to_dict(self) -> Dict[str, Any]:
        return {"latency_ms": self.latency_ms, "target": self.target,
                "error_target": self.error_target}


def objectives_from_env() -> Dict[str, SLOObjective]:
    """Parse the env-declared objective set.  `"*"` (or the
    KFS_SLO_DEFAULT_* pair) declares a wildcard applied to every model
    that has traffic.  Malformed JSON or knobs log and are skipped —
    a bad objective must not take the server down."""
    objectives: Dict[str, SLOObjective] = {}
    raw = os.environ.get(ENV_OBJECTIVES)
    if raw:
        try:
            parsed = json.loads(raw)
            if not isinstance(parsed, dict):
                raise ValueError("must be a JSON object keyed by model")
            for model, spec in parsed.items():
                if not isinstance(spec, dict):
                    raise ValueError(f"objective for {model!r} must be "
                                     "an object")
                objectives[model] = SLOObjective(
                    model,
                    latency_ms=spec.get("latency_ms"),
                    target=spec.get("target", DEFAULT_TARGET),
                    error_target=spec.get("error_target"))
        except (ValueError, TypeError) as e:
            logger.error("malformed %s (%s); ignoring", ENV_OBJECTIVES, e)
            objectives = {}
    default_latency = os.environ.get(ENV_DEFAULT_LATENCY)
    if default_latency and "*" not in objectives:
        try:
            objectives["*"] = SLOObjective(
                "*", latency_ms=float(default_latency),
                target=float(os.environ.get(ENV_DEFAULT_TARGET,
                                            DEFAULT_TARGET)))
        except ValueError:
            logger.error("non-numeric %s / %s; ignoring",
                         ENV_DEFAULT_LATENCY, ENV_DEFAULT_TARGET)
    return objectives


class SLOEngine:
    """Burn-rate evaluation over one or more metrics registries (the
    server's private request registry, plus any others)."""

    def __init__(self, registries: Sequence,
                 objectives: Optional[Dict[str, SLOObjective]] = None,
                 windows_s: Sequence[float] = DEFAULT_WINDOWS_S,
                 burn_alert: float = DEFAULT_BURN_ALERT,
                 total_series: str = REQUEST_TOTAL_SERIES,
                 latency_series: str = REQUEST_LATENCY_SERIES,
                 export_gauges: bool = True):
        self.registries = list(registries)
        # Which cumulative series the burn rates are computed over.
        # Replicas evaluate their own request series (the default);
        # the control plane's predictive loop (control/predictive.py)
        # evaluates the router's per-revision series — same math, same
        # multi-window rule, different vantage point.  Any counter
        # with model/status labels and any histogram with a model
        # label fit the snapshot shape.
        self.total_series = total_series
        self.latency_series = latency_series
        # The control-plane instance must not fight the replicas'
        # engines over the kfserving_tpu_slo_* gauge children (both
        # label by model): secondary engines evaluate silently.
        self.export_gauges = export_gauges
        self.objectives = dict(objectives or {})
        self.windows_s = tuple(sorted(float(w) for w in windows_s))
        self.burn_alert = float(burn_alert)
        # (monotonic time, {model: sample}) history, pruned past the
        # longest window.
        self._snapshots: List[Tuple[float, Dict[str, Dict]]] = []
        self._alerting: Dict[str, bool] = {}
        self._last_report: Dict[str, Any] = {}
        # Breach-transition taps: called as (model, alerting,
        # burn_rates) on every healthy->alerting and alerting->healthy
        # edge — the incident engine opens/recovers incidents off this
        # edge instead of polling the report.  Listeners must not
        # raise; a broken tap is logged and skipped.
        self.transition_listeners: List[Any] = []

    @classmethod
    def from_env(cls, registries: Sequence) -> "SLOEngine":
        from kfserving_tpu.observability.monitoring.knobs import (
            env_number,
        )

        raw_windows = os.environ.get(ENV_WINDOWS, "")
        windows: List[float] = []
        for part in raw_windows.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                windows.append(float(part))
            except ValueError:
                logger.warning("ignoring non-numeric window %r in %s",
                               part, ENV_WINDOWS)
        return cls(registries, objectives_from_env(),
                   windows_s=windows or DEFAULT_WINDOWS_S,
                   burn_alert=env_number(ENV_BURN_ALERT,
                                         DEFAULT_BURN_ALERT))

    @property
    def enabled(self) -> bool:
        return bool(self.objectives)

    def objective_for(self, model: str) -> Optional[SLOObjective]:
        return self.objectives.get(model) or self.objectives.get("*")

    def alerting(self, model: str) -> bool:
        return self._alerting.get(model, False)

    # -- series reading ----------------------------------------------------
    def _snapshot(self) -> Dict[str, Dict]:
        """Cumulative per-model sample: total/error request counts and
        summed latency-histogram bucket counts (verbs merged — an SLO
        covers the model, not one verb)."""
        snap: Dict[str, Dict] = {}

        def entry(model: str) -> Dict:
            return snap.setdefault(model, {
                "total": 0.0, "errors": 0.0,
                "lat_buckets": None, "lat_counts": None,
                "lat_total": 0.0})

        for registry in self.registries:
            fam = registry.family(self.total_series)
            if fam is not None and fam.kind == "counter":
                for labels, child in fam.samples():
                    model = labels.get("model")
                    if model is None:
                        continue
                    e = entry(model)
                    e["total"] += child.value
                    try:
                        if int(labels.get("status", 0)) >= 500:
                            e["errors"] += child.value
                    except ValueError:
                        pass
            fam = registry.family(self.latency_series)
            if fam is not None and fam.kind == "histogram":
                for labels, hist in fam.samples():
                    model = labels.get("model")
                    if model is None:
                        continue
                    with hist._lock:
                        counts = list(hist.counts)
                        total = hist.total
                    e = entry(model)
                    if e["lat_counts"] is None:
                        e["lat_buckets"] = list(hist.buckets)
                        e["lat_counts"] = [0.0] * len(counts)
                    if len(counts) == len(e["lat_counts"]):
                        e["lat_counts"] = [a + b for a, b in
                                           zip(e["lat_counts"], counts)]
                        e["lat_total"] += total
        return snap

    @staticmethod
    def _good_below(sample: Dict, threshold_ms: float) -> float:
        """Observations at or under the largest bucket bound <=
        threshold (the conservative rounding documented above)."""
        buckets, counts = sample["lat_buckets"], sample["lat_counts"]
        if not buckets or counts is None:
            return 0.0
        idx = bisect.bisect_right(buckets, threshold_ms)
        return float(sum(counts[:idx]))

    # -- evaluation --------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Snapshot the series, evaluate every objective over every
        window, export the gauges, and return the report served at
        /v2/health/slo."""
        now = time.monotonic() if now is None else now
        snap = self._snapshot()
        self._snapshots.append((now, snap))
        horizon = now - self.windows_s[-1] if self.windows_s else now
        # Keep one snapshot at-or-before the horizon as the longest
        # window's baseline.
        while len(self._snapshots) > 2 and \
                self._snapshots[1][0] <= horizon:
            self._snapshots.pop(0)
        while len(self._snapshots) > MAX_SNAPSHOTS:
            self._snapshots.pop(0)

        models: Dict[str, Any] = {}
        alerting: List[str] = []
        for model in sorted(snap):
            objective = self.objective_for(model)
            if objective is None:
                continue
            burn_rates: Dict[str, Dict[str, float]] = {}
            component_alerts: Dict[str, bool] = {}
            for window in self.windows_s:
                base = self._baseline(now - window)
                rates = self._burn(objective, snap.get(model),
                                   base.get(model) if base else None)
                for component, rate in rates.items():
                    burn_rates.setdefault(component, {})[
                        _window_label(window)] = round(rate, 4)
                    alerts = component_alerts.setdefault(component,
                                                         True)
                    component_alerts[component] = \
                        alerts and rate > self.burn_alert
                    # Rounded: 0.1/0.01 renders as 10, not
                    # 9.99999999999999, in the exposition.
                    if self.export_gauges:
                        obs.slo_burn_rate().labels(
                            model=model, objective=component,
                            window=_window_label(window)).set(
                                round(rate, 6))
            is_alerting = any(component_alerts.values()) \
                if component_alerts else False
            was = self._alerting.get(model, False)
            self._alerting[model] = is_alerting
            if self.export_gauges:
                obs.slo_alert_state().labels(model=model).set(
                    1.0 if is_alerting else 0.0)
            if is_alerting and not was:
                if self.export_gauges:
                    obs.slo_breaches_total().labels(model=model).inc()
                logger.warning("SLO alert for model %s: burn rates %s "
                               "(threshold %s)", model, burn_rates,
                               self.burn_alert)
                self._notify_transition(model, True, burn_rates)
            elif was and not is_alerting:
                self._notify_transition(model, False, burn_rates)
            models[model] = {
                "objective": objective.to_dict(),
                "burn_rates": burn_rates,
                "alerting": is_alerting,
            }
            if is_alerting:
                alerting.append(model)
        self._last_report = {
            "healthy": not alerting,
            "alerting": alerting,
            "burn_alert_threshold": self.burn_alert,
            "windows_s": list(self.windows_s),
            "models": models,
        }
        return self._last_report

    def _notify_transition(self, model: str, alerting: bool,
                           burn_rates: Dict[str, Any]) -> None:
        for listener in list(self.transition_listeners):
            try:
                listener(model, alerting, dict(burn_rates))
            except Exception:
                logger.exception("SLO transition listener failed")

    def _baseline(self, at: float) -> Optional[Dict[str, Dict]]:
        """Newest snapshot taken at or before `at`; when history is
        still shorter than the window, the oldest held snapshot (a
        young replica evaluates over its whole life — better an
        honest short window than no signal).  On the very first tick
        there is no earlier snapshot at all: the baseline is zero, so
        everything the counters accumulated counts as in-window
        (diffing the snapshot against itself would read burn 0
        forever)."""
        base = None
        for t, s in self._snapshots:
            if t <= at:
                base = s
            else:
                break
        if base is None and len(self._snapshots) > 1:
            base = self._snapshots[0][1]
        return base

    def _burn(self, objective: SLOObjective,
              current: Optional[Dict],
              base: Optional[Dict]) -> Dict[str, float]:
        """Burn rate per component over one window's delta."""
        rates: Dict[str, float] = {}
        if current is None:
            return rates
        if objective.latency_ms is not None and \
                current.get("lat_counts") is not None:
            total = current["lat_total"] - (
                base["lat_total"] if base
                and base.get("lat_counts") is not None else 0.0)
            good = self._good_below(current, objective.latency_ms)
            if base and base.get("lat_counts") is not None:
                good -= self._good_below(base, objective.latency_ms)
            # The latency SLI is "SUCCESSFUL requests under X ms": a
            # hard-down model failing fast would otherwise land every
            # 5xx under the bound and report a healthy latency SLO
            # with zero working requests.  The histogram carries no
            # status label, so subtract the window's 5xx delta from
            # the good count (conservative: assumes errors were fast).
            errors = current["errors"] - (base["errors"] if base
                                          else 0.0)
            good = max(0.0, good - errors)
            if total > 0:
                bad_frac = max(0.0, 1.0 - good / total)
                rates["latency"] = bad_frac / (1.0 - objective.target)
            else:
                rates["latency"] = 0.0
        if objective.error_target is not None:
            total = current["total"] - (base["total"] if base else 0.0)
            errors = current["errors"] - (base["errors"] if base
                                          else 0.0)
            if total > 0:
                rates["errors"] = (errors / total) / \
                    (1.0 - objective.error_target)
            else:
                rates["errors"] = 0.0
        return rates

    def report(self) -> Dict[str, Any]:
        """The last tick's evaluation (fresh tick when none yet)."""
        if not self._last_report:
            return self.tick()
        return self._last_report
