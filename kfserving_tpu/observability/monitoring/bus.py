"""Monitor bus: a bounded, sampling, never-blocking in-process tee.

PR 1/2 left the payload consumers stranded: `agent/logger.py` tees
CloudEvents to an *external* sink, and the drift/outlier detectors only
run when deployed as separate logger-fed services.  The bus is the
in-process equivalent of that CloudEvents hop — the sidecar-free data
plane tees each served request to async consumers (online monitors)
through a bounded queue, with the same backpressure decision the logger
made: when monitoring can't keep up, SAMPLES are dropped (and counted),
never requests.

Delivery contract: one published event is one immutable dict handed to
each consumer whole and in order — a consumer never sees a partial or
interleaved payload, because events are only ever enqueued complete and
the dispatcher awaits one consumer call at a time per event.

Hot-path cost: with no consumers subscribed, `publish()` is one
attribute check.  With consumers, it is a sample draw plus a
`put_nowait` — never an await.
"""

import asyncio
import logging
import random
from typing import Any, Awaitable, Callable, Dict, List, Optional

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.monitoring.knobs import env_number

logger = logging.getLogger("kfserving_tpu.monitoring.bus")

DEFAULT_QUEUE_SIZE = 256
DEFAULT_SAMPLE_RATE = 1.0

Consumer = Callable[[Dict[str, Any]], Awaitable[None]]


class MonitorBus:
    """Bounded async fan-out of request events to monitor consumers."""

    def __init__(self, queue_size: int = DEFAULT_QUEUE_SIZE,
                 sample_rate: float = DEFAULT_SAMPLE_RATE,
                 seed: int = 0):
        self.queue_size = max(1, int(queue_size))
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=self.queue_size)
        self._consumers: List[Consumer] = []
        self._rng = random.Random(seed)
        self._task: Optional[asyncio.Task] = None
        self._warned_drop = False

    @classmethod
    def from_env(cls) -> "MonitorBus":
        return cls(
            queue_size=int(env_number("KFS_MONITOR_QUEUE",
                                      DEFAULT_QUEUE_SIZE)),
            sample_rate=env_number("KFS_MONITOR_SAMPLE",
                                   DEFAULT_SAMPLE_RATE))

    # -- consumers ---------------------------------------------------------
    def subscribe(self, consumer: Consumer) -> None:
        self._consumers.append(consumer)

    @property
    def has_consumers(self) -> bool:
        return bool(self._consumers)

    # -- hot path ----------------------------------------------------------
    def publish(self, event: Dict[str, Any]) -> bool:
        """Offer one event; True when enqueued.  Never blocks and never
        raises: a full queue drops the SAMPLE (counted), not the
        request.  With no consumers the event is discarded for free —
        an unconsumed tee must cost the serving path nothing."""
        if not self._consumers:
            return False
        if self.sample_rate < 1.0 and \
                self._rng.random() >= self.sample_rate:
            obs.monitor_events_total().labels(
                outcome="sampled_out").inc()
            return False
        try:
            self.queue.put_nowait(event)
        except asyncio.QueueFull:
            obs.monitor_events_total().labels(outcome="dropped").inc()
            if not self._warned_drop:
                self._warned_drop = True
                logger.warning(
                    "monitor bus queue full (size %d): dropping "
                    "samples; monitors fell behind the serving rate "
                    "(further drops counted, not logged)",
                    self.queue_size)
            return False
        obs.monitor_events_total().labels(outcome="published").inc()
        return True

    def attach(self, server) -> None:
        """Tee the ModelServer's request hook point onto the bus (the
        same attachment the CloudEvents payload logger uses).  The
        event carries the raw request body — immutable bytes, so the
        consumer-side decode can never observe a half-written
        payload."""
        from kfserving_tpu.tracing import current_request_id

        def hook(name, verb, req, resp, latency_ms):
            if not self._consumers:
                return
            self.publish({
                "model": name,
                "verb": verb,
                "status": resp.status if resp is not None else 200,
                "latency_ms": latency_ms,
                "trace_id": current_request_id.get(),
                "payload": req.body,
            })

        server.request_hooks.append(hook)

    # -- dispatcher --------------------------------------------------------
    async def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._dispatch())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def drain(self) -> None:
        """Wait until every queued event has been dispatched (tests)."""
        await self.queue.join()

    async def _dispatch(self) -> None:
        while True:
            event = await self.queue.get()
            try:
                # Sequential delivery: each consumer gets the whole
                # event before the next consumer (and the next event)
                # runs — ordering and atomicity over throughput, the
                # right trade for windowed statistics.
                for consumer in list(self._consumers):
                    try:
                        await consumer(event)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        name = getattr(consumer, "name",
                                       type(consumer).__name__)
                        obs.monitor_consumer_errors_total().labels(
                            consumer=str(name)).inc()
                        logger.exception(
                            "monitor consumer %s failed", name)
            finally:
                self.queue.task_done()
