"""Unified telemetry for the sidecar-free TPU serving stack.

The reference delegates request telemetry to the Istio/Knative mesh
(queue-proxy traces, controller metrics on :8080); this data plane has
no sidecar, so SURVEY §5.1 makes the serving stack own its spans and
metrics.  This package is the shared substrate:

- `registry` — a process-wide labeled metrics registry (counters /
  gauges / histograms) rendering Prometheus text with OpenMetrics
  exemplars that link latency observations to trace ids.
- `metrics` — the catalog of instrument accessors every layer uses
  (batcher queue-wait, engine stage timings, LLM TTFT/ITL/TPS,
  breaker/retry/deadline series).  Accessors re-resolve from the
  registry on every call, so a test-time `REGISTRY.reset()` never
  leaves a stale instrument behind.
- `accesslog` — one structured JSON line per request (trace id,
  model, verb, status, stage timings, token counts).
- `federation` — /metrics relabeling helpers for the ingress router's
  fleet scrape (every replica series re-emitted under a `replica`
  label).
- `monitoring` — the loop that ACTS on the above: monitor bus (a
  bounded, never-blocking request tee), streaming drift/outlier
  monitors, the per-model SLO burn-rate engine behind
  `GET /v2/health/slo`, and the flight recorder behind
  `GET /debug/flightrecorder`.
- `profiling` — the *device*-path counterpart of the request-path
  spans: the engine event timeline ring (waves, chunks, preemptions,
  HOLD windows, device dispatch spans), its Chrome-trace/Perfetto
  export behind `GET /debug/profile`, and the live roofline gauges
  (`kfserving_tpu_engine_mfu`, padding-waste / goodput /
  HBM-bandwidth ratios).

Import discipline: this package imports nothing from `server/`,
`control/`, `engine/`, or `reliability/` — those layers import *it*,
so reliability instrumentation (and everything else) stays cycle-free.
"""

from kfserving_tpu.observability.registry import (
    LATENCY_BUCKETS_MS,
    REGISTRY,
    Registry,
)

__all__ = ["LATENCY_BUCKETS_MS", "REGISTRY", "Registry"]
