"""Per-request cost attribution: device time, tokens, and cache economics.

PR 6 made device time *visible* (the engine timeline renders every wave
and chunk in Perfetto); this module makes it *attributable*: the
generator folds its dispatch accounting into one cost record per
finished request — attributed device milliseconds split by phase,
prefill vs. decode tokens, peak blocks held, and prompt tokens the
prefix cache saved — and hands it here.  The record then:

- lands in the JSON access log (`cost` field) so offline analysis can
  join cost to status/latency per request;
- is embedded in pinned flight-recorder entries (a p99 outlier pin
  shows what the request *cost*, not just how long it took);
- feeds per-model aggregate histograms through the process registry
  (`kfserving_tpu_request_device_ms{model,phase}`,
  `_request_phase_tokens`, `_request_held_blocks`,
  `_request_cache_saved_tokens`), federated by the router like every
  PR-2 series.

Attribution discipline: a dispatch's busy interval is split EVENLY
across the live streams it served, so per-request device ms sum to the
engine's total device time — an additive decomposition (InferLine's
per-stage cost shape, arxiv 1812.01776), not a latency measurement.

The record store is a bounded ring keyed by trace id
(`KFS_ATTRIBUTION_RECORDS`, default 1024): the server's completion
path and the flight recorder look records up moments after the engine
finalizes them, so a small window is plenty.  Lookups are
non-destructive (access log AND pin evaluation both read the same
record).

Import discipline (observability package contract): nothing from
`server/`, `control/`, `engine/`, or `reliability/` — the engine calls
*into* this module, never the reverse.
"""

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Set

from kfserving_tpu.observability import metrics as obs

DEFAULT_RECORDS = 1024

_lock = threading.Lock()
_records: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()


def _capacity() -> int:
    try:
        return max(16, int(os.environ.get("KFS_ATTRIBUTION_RECORDS",
                                          DEFAULT_RECORDS)))
    except ValueError:
        return DEFAULT_RECORDS


def observe(model: str, trace_id: Optional[str],
            record: Dict[str, Any]) -> Dict[str, Any]:
    """Finalize one request's cost record: stamp the model, feed the
    per-model aggregate histograms, and (when traced) store it for the
    access log / flight recorder to attach.  Never raises into the
    engine's completion path."""
    record = dict(record)
    record["model"] = model
    # Wall-clock stamp: the top-coster query (and the incident
    # engine's evidence bundle) filters records by finish time.
    record.setdefault("ts", time.time())
    try:
        device = record.get("device_ms") or {}
        for phase in ("prefill", "decode"):
            ms = device.get(phase)
            if isinstance(ms, (int, float)) and ms > 0:
                obs.request_device_ms().labels(
                    model=model, phase=phase).observe(
                        float(ms), trace_id=trace_id)
        for phase, key in (("prefill", "prefill_tokens"),
                           ("decode", "decode_tokens")):
            n = record.get(key)
            if isinstance(n, (int, float)):
                obs.request_phase_tokens().labels(
                    model=model, phase=phase).observe(float(n))
        blocks = record.get("blocks_held")
        if isinstance(blocks, (int, float)) and blocks > 0:
            obs.request_held_blocks().labels(model=model).observe(
                float(blocks))
        saved = record.get("cache_saved_tokens")
        if isinstance(saved, (int, float)):
            obs.request_cache_saved_tokens().labels(
                model=model).observe(float(saved))
        # Distinct from cache_saved_tokens (device prefix hits): these
        # prompt tokens were recovered from the HOST tier by a
        # fault-back — additive, never double-counted (a block is
        # either a device hit or a host fault, per plan).
        host_saved = record.get("host_tier_saved_tokens")
        if isinstance(host_saved, (int, float)):
            obs.request_host_tier_saved_tokens().labels(
                model=model).observe(float(host_saved))
        if trace_id:
            with _lock:
                _records[trace_id] = record
                _records.move_to_end(trace_id)
                cap = _capacity()
                while len(_records) > cap:
                    _records.popitem(last=False)
    except Exception:
        # Telemetry must never fail a finishing request.
        import logging

        logging.getLogger("kfserving_tpu.attribution").exception(
            "cost attribution failed for %s", model)
    return record


def lookup(trace_id: Optional[str]) -> Optional[Dict[str, Any]]:
    """Non-destructive fetch of a trace's cost record (None when the
    request was untraced, never finished a generation, or rotated out
    of the bounded store)."""
    if not trace_id:
        return None
    with _lock:
        rec = _records.get(trace_id)
        return dict(rec) if rec is not None else None


def recent(limit: int = 10) -> List[Dict[str, Any]]:
    """Newest `limit` records (bench evidence / debugging)."""
    limit = max(0, int(limit))
    with _lock:
        return [dict(r) for r in list(_records.values())[-limit:]]


def total_device_ms(record: Dict[str, Any]) -> float:
    """A record's attributed device milliseconds summed over phases."""
    device = record.get("device_ms") or {}
    total = 0.0
    for ms in device.values():
        if isinstance(ms, (int, float)):
            total += float(ms)
    return total


def top(k: int = 10, window_s: Optional[float] = None,
        by: str = "device_ms",
        now: Optional[float] = None) -> List[Dict[str, Any]]:
    """Top-K cost records from the ring, ranked by attributed device
    milliseconds (`by="device_ms"`, summed over phases) or peak blocks
    held (`by="held_blocks"`).  `window_s` keeps only records whose
    finish stamp falls inside the trailing window — the incident
    engine's evidence bundle asks for "the most expensive requests of
    the breach window", `kfs cache --top-cost` asks the same question
    interactively.  Each returned copy carries its computed
    `total_device_ms` so rankings are self-explanatory."""
    if by not in ("device_ms", "held_blocks"):
        raise ValueError("by must be device_ms or held_blocks")
    k = max(0, int(k))
    now = time.time() if now is None else now
    with _lock:
        records = [dict(r) for r in _records.values()]
    if window_s is not None:
        horizon = now - float(window_s)
        records = [r for r in records
                   if float(r.get("ts") or 0.0) >= horizon]
    for r in records:
        r["total_device_ms"] = round(total_device_ms(r), 3)
    if by == "device_ms":
        records.sort(key=lambda r: r["total_device_ms"], reverse=True)
    else:
        records.sort(key=lambda r: float(r.get("blocks_held") or 0.0),
                     reverse=True)
    return records[:k]


def clear() -> None:
    with _lock:
        _records.clear()


def _clamp01(value: float) -> float:
    return min(1.0, max(0.0, float(value)))


def publish_cache_gauges(model: str, stats: Dict[str, Any]) -> Set[str]:
    """Promote an engine stats dict's paged-pool ratios into registry
    gauges at /metrics scrape time (the roofline.publish_gauges shape).
    Returns the consumed TOP-LEVEL stat keys — none today: the `paged`
    dict keeps its legacy per-key export (tests and dashboards read
    `kfserving_tpu_engine_paged{bucket=...}`), the ratio gauges are
    published IN ADDITION so the `_ratio` unit contract holds."""
    consumed: Set[str] = set()
    try:
        paged = stats.get("paged")
        if isinstance(paged, dict):
            occ = paged.get("pool_occupancy_ratio")
            if isinstance(occ, (int, float)):
                obs.generator_pool_occupancy_ratio().labels(
                    model=model).set(_clamp01(occ))
            frag = paged.get("fragmentation_ratio")
            if isinstance(frag, (int, float)):
                obs.generator_pool_fragmentation_ratio().labels(
                    model=model).set(_clamp01(frag))
    except Exception:
        import logging

        logging.getLogger("kfserving_tpu.attribution").exception(
            "cache gauge publish failed for %s", model)
    return consumed
