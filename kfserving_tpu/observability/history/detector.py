"""EWMA + z-score change-point detection over the history rings.

The sampler's rings answer "what happened"; this detector answers
"when did it CHANGE" — cheaply enough to run on every tick.  Each
watched series (family names in `KFS_HISTORY_WATCH`, defaulting to
the latency / error-ratio / occupancy / hit-rate leading indicators)
carries per-label-set state: an exponentially weighted mean and
variance plus an EWMA'd first derivative (the trend slope).  A new
sample whose z-score against the pre-change mean exceeds the
threshold for `KFS_HISTORY_WATCH_TICKS` consecutive ticks, after a
`KFS_HISTORY_WATCH_MIN_SAMPLES` warmup, is a change-point:

- a `trend_<series>` entry is pinned into the flight recorder
  embedding the pre/post window frames around the breach — the
  "what led up to this" evidence a request-timeline pin lacks;
- `kfserving_tpu_trend_changepoints_total` increments;
- the baseline re-seeds at the new level and a cooldown suppresses
  re-pinning while the series settles.

Continuously (not just at change-points) the detector exports
`kfserving_tpu_trend_slope_per_second` and
`kfserving_tpu_trend_zscore` gauges labeled
`{series=<name>, ...underlying labels}` — the slope gauge is the
leading input the predictive scaler's slope-aware gap sizing
consumes.  Gauge children are pruned when the underlying series is
swept from the store, so a dead revision's trend series dies with
its rings.

The flight recorder is injected by the owning server (import
discipline: this package reaches neither monitoring's recorder nor
the control plane).
"""

import logging
import math
import os
from typing import Dict, List, Optional, Tuple

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.history.store import HistoryStore

logger = logging.getLogger("kfserving_tpu.observability.history")

ENV_WATCH = "KFS_HISTORY_WATCH"
ENV_ALPHA = "KFS_HISTORY_WATCH_ALPHA"
ENV_Z = "KFS_HISTORY_WATCH_Z"
ENV_MIN_SAMPLES = "KFS_HISTORY_WATCH_MIN_SAMPLES"
ENV_TICKS = "KFS_HISTORY_WATCH_TICKS"
ENV_COOLDOWN = "KFS_HISTORY_WATCH_COOLDOWN_S"
ENV_WINDOW = "KFS_HISTORY_WATCH_WINDOW_S"

# Leading indicators every deployment has: time-to-first-token and
# request latency tails, the error ratio, pool pressure, and prefix
# cache effectiveness.  `KFS_HISTORY_WATCH` (comma-separated family
# names) replaces the list wholesale.
DEFAULT_WATCHES = (
    "kfserving_tpu_llm_ttft_ms_p99",
    "kfserving_tpu_request_latency_ms_p99",
    "kfserving_tpu_revision_request_ms_p99",
    "kfserving_tpu_history_error_ratio",
    "kfserving_tpu_generator_pool_occupancy_ratio",
    "kfserving_tpu_history_prefix_hit_ratio",
)

DEFAULT_ALPHA = 0.3
DEFAULT_Z = 4.0
DEFAULT_MIN_SAMPLES = 20
DEFAULT_TICKS = 3
DEFAULT_COOLDOWN_S = 60.0
DEFAULT_WINDOW_S = 120.0

# The z-score denominator floor: a flat-lined series (variance ~0)
# must not turn the first real fluctuation into a division-by-epsilon
# z in the thousands — std is floored at 5% of the level and an
# absolute epsilon.
_REL_STD_FLOOR = 0.05
_ABS_STD_FLOOR = 1e-3


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default


class _SeriesState:
    __slots__ = ("ewma", "var", "slope", "streak", "streak_start_ts",
                 "cooldown_until", "n", "last_ts", "last_value",
                 "last_z")

    def __init__(self):
        self.ewma = 0.0
        self.var = 0.0
        self.slope = 0.0
        self.streak = 0
        self.streak_start_ts = 0.0
        self.cooldown_until = 0.0
        self.n = 0
        self.last_ts: Optional[float] = None
        self.last_value = 0.0
        self.last_z = 0.0


class TrendDetector:
    """Per-watched-series EWMA/z-score state machine; `evaluate()`
    runs at the end of every sampler tick."""

    def __init__(self, store: HistoryStore,
                 watches: Optional[List[str]] = None,
                 recorder=None,
                 alpha: Optional[float] = None,
                 z_threshold: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 breach_ticks: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 window_s: Optional[float] = None):
        self.store = store
        if watches is None:
            raw = os.environ.get(ENV_WATCH, "")
            watches = ([w.strip() for w in raw.split(",") if w.strip()]
                       if raw.strip() else list(DEFAULT_WATCHES))
        self.watches = list(watches)
        self.recorder = recorder
        self.alpha = (alpha if alpha is not None
                      else _env_float(ENV_ALPHA, DEFAULT_ALPHA))
        self.z_threshold = (
            z_threshold if z_threshold is not None
            else _env_float(ENV_Z, DEFAULT_Z))
        self.min_samples = int(
            min_samples if min_samples is not None
            else _env_float(ENV_MIN_SAMPLES, DEFAULT_MIN_SAMPLES))
        self.breach_ticks = int(
            breach_ticks if breach_ticks is not None
            else _env_float(ENV_TICKS, DEFAULT_TICKS))
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else _env_float(ENV_COOLDOWN, DEFAULT_COOLDOWN_S))
        self.window_s = (
            window_s if window_s is not None
            else _env_float(ENV_WINDOW, DEFAULT_WINDOW_S))
        self._state: Dict[tuple, _SeriesState] = {}
        self.changepoints = 0

    # -- the per-tick pass ------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> int:
        """Advance every watched series by its newest frame; returns
        the number of change-points declared this pass."""
        if now is None:
            import time

            now = time.time()
        declared = 0
        seen: set = set()
        for name, labels, kind, frames in self.store.watched(
                self.watches):
            if not frames:
                continue
            key = (name, tuple(sorted(labels.items())))
            seen.add(key)
            state = self._state.get(key)
            if state is None:
                state = self._state[key] = _SeriesState()
            # Only frames this state machine has not consumed yet —
            # an idle series (no new frame) advances nothing.
            fresh = [f for f in frames
                     if state.last_ts is None or f[0] > state.last_ts]
            for ts, value in fresh:
                if self._step(name, labels, state, ts, value,
                              frames, now):
                    declared += 1
            self._export(name, labels, state)
        self._prune_stale(seen)
        return declared

    def _step(self, name: str, labels: Dict[str, str],
              state: _SeriesState, ts: float, value: float,
              frames: List[Tuple[float, float]],
              now: float) -> bool:
        if state.last_ts is not None and ts > state.last_ts:
            dv_dt = (value - state.last_value) / (ts - state.last_ts)
            state.slope += self.alpha * (dv_dt - state.slope)
        state.last_ts = ts
        state.last_value = value
        if state.n == 0:
            state.ewma = value
            state.n = 1
            return False
        std = max(math.sqrt(max(state.var, 0.0)),
                  _REL_STD_FLOOR * abs(state.ewma), _ABS_STD_FLOOR)
        z = (value - state.ewma) / std
        state.last_z = z
        breaching = (state.n >= self.min_samples
                     and abs(z) >= self.z_threshold)
        if breaching:
            if state.streak == 0:
                state.streak_start_ts = ts
            state.streak += 1
            # The baseline holds still during a suspected shift so a
            # slow ramp can't drag the mean along and never breach.
            if (state.streak >= self.breach_ticks
                    and ts >= state.cooldown_until):
                self._changepoint(name, labels, state, ts, value, z,
                                  frames)
                return True
            return False
        state.streak = 0
        diff = value - state.ewma
        incr = self.alpha * diff
        state.ewma += incr
        state.var = (1.0 - self.alpha) * (state.var + diff * incr)
        state.n += 1
        return False

    def _changepoint(self, name: str, labels: Dict[str, str],
                     state: _SeriesState, ts: float, value: float,
                     z: float,
                     frames: List[Tuple[float, float]]) -> None:
        self.changepoints += 1
        split = state.streak_start_ts
        half = self.window_s / 2.0
        pre = [[t, v] for t, v in frames
               if split - half <= t < split]
        post = [[t, v] for t, v in frames
                if split <= t <= split + half]
        pin = "trend_" + name
        entry = {
            "kind": "trend",
            "series": name,
            "labels": dict(labels),
            "ts": ts,
            "value": value,
            "baseline": state.ewma,
            "z": z,
            "slope_per_s": state.slope,
            "breach_start_ts": split,
            "pre": pre,
            "post": post,
        }
        if self.recorder is not None:
            try:
                self.recorder.record(entry, pin=pin)
            except Exception:
                logger.exception("trend pin failed")
        obs.trend_changepoints_total().labels(series=name).inc()
        logger.warning(
            "change-point on %s%s: %.4g -> %.4g (z=%.1f)",
            name, labels, state.ewma, value, z)
        # Re-seed at the new level: the shifted regime is the new
        # normal, and the cooldown absorbs its settling noise.
        state.ewma = value
        state.var = 0.0
        state.n = max(state.n, self.min_samples)
        state.streak = 0
        state.cooldown_until = ts + self.cooldown_s

    # -- gauge export -----------------------------------------------------
    def _export(self, name: str, labels: Dict[str, str],
                state: _SeriesState) -> None:
        merged = dict(labels)
        merged["series"] = name
        obs.trend_slope_per_second().labels(**merged).set(state.slope)
        obs.trend_zscore().labels(**merged).set(state.last_z)

    def _prune_stale(self, seen: set) -> None:
        """Drop detector state and exported gauge children for series
        the store swept (pruned revision, reset) — trend gauges must
        not outlive their source rings."""
        for key in [k for k in self._state if k not in seen]:
            del self._state[key]
            name, label_key = key
            merged = dict(label_key)
            merged["series"] = name
            obs.trend_slope_per_second().prune(**merged)
            obs.trend_zscore().prune(**merged)
