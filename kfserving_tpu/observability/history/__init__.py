"""Continuous telemetry history: the missing fourth leg beside
metrics, traces, and profiles.

Every other telemetry surface in the stack is instantaneous — the
registry answers "what is true right now at scrape time" — so nobody
could answer "when did TTFT p99 start degrading" or "what did pool
occupancy look like in the two minutes before the eviction storm".
This package retains that time axis in-process, bounded and
allocation-light:

- `store`    — `HistoryStore`, a ring TSDB: fixed-size per-series
               rings with downsampled retention tiers (default
               1 s x 10 min -> 10 s x 2 h), so memory stays fixed no
               matter how long the replica lives.
- `sampler`  — `HistorySampler`, the background tick (default 1 s)
               that walks every registry family: counters land as
               per-second rates (deltas over the tick), gauges as
               values, histograms as per-bucket deltas reduced to
               derived `_p50`/`_p99`/`_count` series; plus synthetic
               `kfserving_tpu_history_error_ratio` /
               `_prefix_hit_ratio` series derived across label sets.
               Scrape-time publishers (roofline gauges, pool ratios)
               run ON the tick so live scrapes and history agree.
- `detector` — `TrendDetector`, EWMA + z-score change-point detection
               per watched series (KFS_HISTORY_WATCH*), pinning a
               `trend_<series>` flight-recorder entry that embeds the
               pre/post window frames and exporting trend-slope
               gauges the predictive scaler consumes as a leading
               input.

Served per replica at `GET /debug/history?series=&labels=&window_s=&
step_s=`, federated by the ingress router under the `replica` label
with a fleet rollup, and reachable from the SDK via
`client.history()` / `kfs history <series>`.

Import discipline (observability package contract): nothing from
`server/`, `control/`, `engine/`, or `reliability/` — the fault-site
hook and the scrape-time publishers are injected by the server that
owns the sampler.
"""

from kfserving_tpu.observability.history.detector import (
    DEFAULT_WATCHES,
    TrendDetector,
)
from kfserving_tpu.observability.history.sampler import (
    DEFAULT_TICK_S,
    ENV_ENABLE,
    ENV_TICK,
    HistorySampler,
    history_enabled,
)
from kfserving_tpu.observability.history.store import (
    DEFAULT_TIERS,
    HistoryStore,
)

__all__ = [
    "HistoryStore", "HistorySampler", "TrendDetector",
    "DEFAULT_TIERS", "DEFAULT_TICK_S", "DEFAULT_WATCHES",
    "ENV_ENABLE", "ENV_TICK", "history_enabled",
]
