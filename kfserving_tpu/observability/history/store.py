"""Bounded in-process ring TSDB with downsampled retention tiers.

One `HistoryStore` holds many series, each identified by (family
name, label set) and carrying a `kind` that fixes its aggregation
semantics:

    gauge      instantaneous value          (downsample/rollup: mean)
    rate       counter delta / tick seconds (mean; fleet rollup: sum)
    quantile   derived histogram quantile   (mean)
    ratio      synthetic 0..1 ratio         (mean)

Memory is fixed by construction: every series owns one preallocated
`array('d')` ring per retention tier (default 1 s x 600 samples and
10 s x 720 samples ~= 10 min fine + 2 h coarse, ~21 KB per series),
and the series population is capped (`max_series`, overflow counted
in `self.dropped`, never raised).  Appends are allocation-free ring
writes; the coarse tiers fill from a running (sum, count) accumulator
flushed on step-boundary crossings, so a tier-1 point is the mean of
the tier-0 points in its 10 s window.

Thread model: the sampler appends from its tick while `/debug/history`
queries from the event loop — one store-wide lock guards the series
map and every ring mutation (all operations are short, in-memory
walks; nothing blocks under the lock).
"""

import math
import threading
from array import array
from typing import Dict, Iterable, List, Optional, Tuple

# (step seconds multiplier over the tick, capacity): tier 0 retains
# tick_s x 600 (10 min at the 1 s default), tier 1 retains
# 10 x tick_s x 720 (2 h at the default).
DEFAULT_TIERS = ((1, 600), (10, 720))

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Ring:
    """Fixed-capacity (ts, value) circular buffer."""

    __slots__ = ("step_s", "capacity", "_ts", "_val", "_head",
                 "_count")

    def __init__(self, step_s: float, capacity: int):
        self.step_s = step_s
        self.capacity = capacity
        self._ts = array("d", [0.0]) * capacity
        self._val = array("d", [0.0]) * capacity
        self._head = 0   # next write slot
        self._count = 0

    def append(self, ts: float, value: float) -> None:
        self._ts[self._head] = ts
        self._val[self._head] = value
        self._head = (self._head + 1) % self.capacity
        self._count = min(self._count + 1, self.capacity)

    def span_s(self) -> float:
        return self.step_s * self.capacity

    def frames(self, since: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """Chronological (ts, value) pairs, optionally since a
        timestamp."""
        start = (self._head - self._count) % self.capacity
        out: List[Tuple[float, float]] = []
        for i in range(self._count):
            j = (start + i) % self.capacity
            if since is None or self._ts[j] >= since:
                out.append((self._ts[j], self._val[j]))
        return out


class _Series:
    __slots__ = ("name", "labels", "kind", "rings", "_acc")

    def __init__(self, name: str, labels: Dict[str, str], kind: str,
                 tiers: Iterable[Tuple[float, int]]):
        self.name = name
        self.labels = dict(labels)
        self.kind = kind
        self.rings = [_Ring(step, cap) for step, cap in tiers]
        # Per coarse tier: [bucket start ts or None, sum, count].
        self._acc = [[None, 0.0, 0] for _ in self.rings[1:]]

    def append(self, ts: float, value: float) -> None:
        self.rings[0].append(ts, value)
        for i, ring in enumerate(self.rings[1:]):
            bucket = math.floor(ts / ring.step_s) * ring.step_s
            acc = self._acc[i]
            if acc[0] is not None and bucket != acc[0]:
                ring.append(acc[0], acc[1] / max(1, acc[2]))
                acc[0], acc[1], acc[2] = None, 0.0, 0
            if acc[0] is None:
                acc[0] = bucket
            acc[1] += value
            acc[2] += 1

    def points(self) -> int:
        return sum(r._count for r in self.rings)


class HistoryStore:
    def __init__(self, tick_s: float = 1.0,
                 tiers: Optional[Iterable[Tuple[float, int]]] = None,
                 max_series: int = 4096):
        self.tick_s = tick_s
        if tiers is None:
            tiers = [(mult * tick_s, cap)
                     for mult, cap in DEFAULT_TIERS]
        self.tiers: List[Tuple[float, int]] = [
            (float(step), int(cap)) for step, cap in tiers]
        self.max_series = max_series
        self.dropped = 0  # series refused at the population cap
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, _LabelKey], _Series] = {}

    # -- writes (sampler tick) -------------------------------------------
    def record(self, name: str, labels: Optional[Dict[str, str]],
               kind: str, ts: float, value: float) -> bool:
        """Append one sample; False when refused at the series cap."""
        key = (name, _label_key(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    self.dropped += 1
                    return False
                series = _Series(name, dict(labels or {}), kind,
                                 self.tiers)
                self._series[key] = series
            series.append(ts, float(value))
            return True

    def sweep(self, live: set) -> int:
        """Drop every series whose (name, label key) is NOT in
        `live` — the set of keys the sampler saw this tick.  A pruned
        registry child's series stops here immediately: it must not
        survive as a ghost ring that a rollout rollback would then
        resurrect with stale frames.  Returns the number dropped."""
        with self._lock:
            gone = [k for k in self._series if k not in live]
            for k in gone:
                del self._series[k]
            return len(gone)

    @staticmethod
    def key(name: str, labels: Optional[Dict[str, str]] = None
            ) -> Tuple[str, _LabelKey]:
        """The sweep/live-set key for one series."""
        return (name, _label_key(labels))

    # -- reads (/debug/history, detector) --------------------------------
    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def index(self) -> List[Dict]:
        """Discovery view: every live series with its kind and point
        count (no frames)."""
        with self._lock:
            items = list(self._series.values())
        return sorted(
            ({"name": s.name, "labels": s.labels, "kind": s.kind,
              "points": s.points()} for s in items),
            key=lambda d: (d["name"], sorted(d["labels"].items())))

    def latest(self, name: str,
               labels: Optional[Dict[str, str]] = None
               ) -> Optional[Tuple[float, float]]:
        """Newest tier-0 frame of one exact series (None if absent
        or empty)."""
        with self._lock:
            series = self._series.get((name, _label_key(labels)))
            if series is None:
                return None
            frames = series.rings[0].frames()
        return frames[-1] if frames else None

    def watched(self, names) -> List[Tuple[str, Dict[str, str], str,
                                           List[Tuple[float, float]]]]:
        """(name, labels, kind, tier-0 frames) for every series whose
        name is in `names` — the detector's per-tick read."""
        wanted = set(names)
        with self._lock:
            items = [s for s in self._series.values()
                     if s.name in wanted]
            return [(s.name, dict(s.labels), s.kind,
                     s.rings[0].frames()) for s in items]

    def query(self, series: Optional[str] = None,
              labels: Optional[Dict[str, str]] = None,
              window_s: float = 600.0,
              step_s: Optional[float] = None,
              now: Optional[float] = None) -> List[Dict]:
        """Aligned (ts, value) frames for every series matching `series`
        (exact family name; None = all) whose labels contain every
        pair in `labels`.

        Frames are resampled onto an absolute epoch grid
        (ts = floor(sample_ts / step) * step, mean per bucket), so the
        router can merge replicas' answers by timestamp.  The source
        tier is the finest whose retention covers `window_s`."""
        if now is None:
            import time

            now = time.time()
        with self._lock:
            matched = [
                s for s in self._series.values()
                if (series is None or s.name == series)
                and (not labels
                     or all(s.labels.get(k) == str(v)
                            for k, v in labels.items()))]
            out = []
            since = now - window_s
            for s in matched:
                ring = s.rings[-1]
                for r in s.rings:
                    if r.span_s() >= window_s:
                        ring = r
                        break
                step = float(step_s) if step_s else ring.step_s
                out.append((s.name, dict(s.labels), s.kind,
                            ring.frames(since), step))
        results = []
        for name, lbls, kind, frames, step in out:
            results.append({
                "name": name, "labels": lbls, "kind": kind,
                "step_s": step,
                "frames": _resample(frames, step)})
        return sorted(results,
                      key=lambda d: (d["name"],
                                     sorted(d["labels"].items())))


def _resample(frames: List[Tuple[float, float]],
              step: float) -> List[List[float]]:
    """Mean-aggregate frames onto the absolute epoch grid."""
    buckets: Dict[float, List[float]] = {}
    order: List[float] = []
    for ts, v in frames:
        b = math.floor(ts / step) * step
        slot = buckets.get(b)
        if slot is None:
            buckets[b] = slot = [0.0, 0.0]
            order.append(b)
        slot[0] += v
        slot[1] += 1.0
    return [[b, buckets[b][0] / buckets[b][1]]
            for b in sorted(order)]
