"""The background tick that turns registry point samples into history.

Each tick (default 1 s, `KFS_HISTORY_TICK_S`):

1. runs the registered scrape-time publishers (the roofline /
   pool-ratio gauges the `/metrics` handler refreshes) so the gauges
   the tick samples are the SAME ones a concurrent live scrape sees —
   between-scrape invisibility was the pre-ISSUE-17 bug;
2. walks every family of every attached registry: counters land as
   per-second rates over the tick (counter resets clamp to the new
   value, never a negative rate), gauges as values, histograms as
   per-bucket deltas reduced to derived `<name>_p50` / `<name>_p99`
   quantile series (linear interpolation inside the winning bucket)
   plus a `<name>_count` rate;
3. derives the synthetic cross-label ratios the watch list wants:
   `kfserving_tpu_history_error_ratio{model=}` (5xx / all request
   deltas) and `kfserving_tpu_history_prefix_hit_ratio{model=}`
   (prefix-lookup hit share);
4. sweeps series whose source sample disappeared (a pruned revision's
   rings die with the prune — no ghost series) and runs the trend
   detector over the fresh frames.

The loop is an asyncio task registered as a server service, so it
dies with the server's loop; the tick body itself is synchronous,
allocation-light, in-memory work (tests and the bench drive `tick()`
directly with pinned timestamps).  The owning server injects an async
`fault_hook` probing the `observability.history_tick` fault site
before each tick: an injected hang parks only this task (history goes
stale-but-served) and an injected error is swallowed and counted in
`kfserving_tpu_history_tick_failures_total` — the serving path never
blocks on, or fails with, its own telemetry.

The first sight of a counter/histogram child only establishes the
delta baseline (no frame): a counter that re-appears after a prune +
rollback therefore restarts from a fresh baseline instead of
inheriting a stale one.
"""

import logging
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.history.store import HistoryStore
from kfserving_tpu.observability.metrics import REQUEST_TOTAL_SERIES
from kfserving_tpu.observability.registry import Registry

logger = logging.getLogger("kfserving_tpu.observability.history")

ENV_ENABLE = "KFS_HISTORY"
ENV_TICK = "KFS_HISTORY_TICK_S"
ENV_MAX_SERIES = "KFS_HISTORY_MAX_SERIES"
DEFAULT_TICK_S = 1.0

# Synthetic cross-label series this sampler derives per tick (their
# sources are counters whose interesting signal is a ratio of label
# slices, which no single registry child carries).
ERROR_RATIO_SERIES = "kfserving_tpu_history_error_ratio"
PREFIX_HIT_RATIO_SERIES = "kfserving_tpu_history_prefix_hit_ratio"
_PREFIX_LOOKUPS_SERIES = "kfserving_tpu_generator_prefix_lookups_total"

# Derived-quantile points per histogram child per tick.
QUANTILES = ((0.5, "_p50"), (0.99, "_p99"))


def history_enabled() -> bool:
    return os.environ.get(ENV_ENABLE, "1") != "0"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default


def _quantile(buckets: List[float], counts: List[int], total: int,
              q: float) -> float:
    """Quantile from per-bucket deltas: linear interpolation inside
    the winning bucket; the +Inf bucket extrapolates past the last
    bound (same 1.5x convention as the predictive scaler's mean)."""
    rank = q * total
    cum = 0.0
    lower = 0.0
    for bound, count in zip(buckets, counts):
        if count > 0:
            if cum + count >= rank:
                return lower + (bound - lower) * \
                    min(1.0, max(0.0, (rank - cum) / count))
            cum += count
        lower = bound
    return buckets[-1] * 1.5 if buckets else 0.0


class HistorySampler:
    """Ticks the registries into a `HistoryStore`; a server service
    (`await start()` / `await stop()`)."""

    def __init__(self, store: Optional[HistoryStore] = None,
                 registries: Optional[List[Registry]] = None,
                 tick_s: Optional[float] = None,
                 detector=None,
                 fault_hook: Optional[Callable] = None,
                 publishers: Optional[List[Callable]] = None):
        self.tick_s = (tick_s if tick_s is not None
                       else _env_float(ENV_TICK, DEFAULT_TICK_S))
        self.tick_s = max(0.01, self.tick_s)
        self.store = store or HistoryStore(
            tick_s=self.tick_s,
            max_series=int(_env_float(ENV_MAX_SERIES, 4096)))
        self.registries: List[Registry] = list(registries or [])
        self.detector = detector
        self._fault_hook = fault_hook
        self.publishers: List[Callable] = list(publishers or [])
        self.ticks = 0
        self.failures = 0
        # Delta baselines, keyed (registry id, family, label key):
        # counters map to their last value, histograms to their last
        # (counts, total) snapshot.
        self._prev_counter: Dict[tuple, float] = {}
        self._prev_hist: Dict[tuple, Tuple[List[int], int]] = {}
        self._last_tick_t: Optional[float] = None
        self._fail_log_t: Optional[float] = None
        self._task = None

    def add_publisher(self, fn: Callable) -> None:
        self.publishers.append(fn)

    # -- service lifecycle ----------------------------------------------
    async def start(self) -> None:
        import asyncio

        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._loop())

    async def stop(self) -> None:
        import asyncio

        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self.tick_s)
            try:
                if self._fault_hook is not None:
                    # Chaos seam (observability.history_tick): an
                    # injected hang parks THIS task only — async
                    # sleep, the serving loop keeps running and
                    # /debug/history serves stale frames.
                    await self._fault_hook()
                self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                self.failures += 1
                obs.history_tick_failures_total().inc()
                # A persistently failing tick would otherwise emit a
                # traceback every tick_s: full exception on the first
                # failure of a streak, then one WARNING per minute;
                # the failure counter carries the exact count.
                now = time.monotonic()
                if self._fail_log_t is None:
                    logger.exception("history tick failed (history is "
                                     "stale-but-served)")
                    self._fail_log_t = now
                elif now - self._fail_log_t >= 60.0:
                    logger.warning(
                        "history tick still failing (%d failures so "
                        "far; history is stale-but-served)",
                        self.failures)
                    self._fail_log_t = now
            else:
                self._fail_log_t = None

    # -- the tick ---------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> int:
        """One sampling pass; returns points recorded.  `now` pins the
        sample timestamp (tests/bench); the delta denominator is the
        gap since the previous tick (first tick assumes `tick_s`)."""
        t0 = time.perf_counter()
        if now is None:
            now = time.time()
        dt = (now - self._last_tick_t
              if self._last_tick_t is not None else self.tick_s)
        dt = max(dt, 1e-6)
        self._last_tick_t = now
        for pub in self.publishers:
            try:
                pub()
            except Exception:
                logger.exception("history publisher failed")
        live: set = set()
        points = 0
        # {model: {outcome-ish: delta}} feeds for the synthetic ratios.
        request_deltas: Dict[str, Dict[str, float]] = {}
        prefix_deltas: Dict[str, Dict[str, float]] = {}
        seen_baselines: set = set()
        for reg in self.registries:
            for name, kind in reg.families().items():
                fam = reg.family(name)
                if fam is None:
                    continue
                for labels, child in fam.samples():
                    if kind == "counter":
                        points += self._sample_counter(
                            reg, name, labels, child, now, dt, live,
                            seen_baselines, request_deltas,
                            prefix_deltas)
                    elif kind == "gauge":
                        key = self.store.key(name, labels)
                        live.add(key)
                        if self.store.record(name, labels, "gauge",
                                             now, child.value):
                            points += 1
                    else:
                        points += self._sample_histogram(
                            reg, name, labels, child, now, dt, live,
                            seen_baselines)
        points += self._synthetic_ratios(now, live, request_deltas,
                                         prefix_deltas)
        # Baselines whose child vanished (prune/reset) go too — a
        # re-registered child must start fresh, not diff against a
        # ghost.
        for prev in (self._prev_counter, self._prev_hist):
            for key in [k for k in prev if k not in seen_baselines]:
                del prev[key]
        swept = self.store.sweep(live)
        self.ticks += 1
        if self.detector is not None:
            try:
                self.detector.evaluate(now)
            except Exception:
                logger.exception("trend detector failed")
        obs.history_samples_total().inc(points)
        obs.history_series().set(self.store.series_count())
        obs.history_tick_ms().observe(
            (time.perf_counter() - t0) * 1000.0)
        if swept:
            logger.debug("history sweep dropped %d series", swept)
        return points

    def _sample_counter(self, reg, name, labels, child, now, dt,
                        live, seen_baselines, request_deltas,
                        prefix_deltas) -> int:
        base_key = (id(reg), name, tuple(sorted(labels.items())))
        seen_baselines.add(base_key)
        cur = child.value
        prev = self._prev_counter.get(base_key)
        self._prev_counter[base_key] = cur
        if prev is None:
            return 0  # baseline only: no frame on first sight
        delta = cur - prev if cur >= prev else cur  # reset-safe
        if name == REQUEST_TOTAL_SERIES:
            model = labels.get("model", "")
            by = request_deltas.setdefault(model, {})
            status = labels.get("status", "")
            bucket = ("error" if status[:1] in ("5",) else "ok")
            by[bucket] = by.get(bucket, 0.0) + delta
        elif name == _PREFIX_LOOKUPS_SERIES:
            model = labels.get("model", "")
            by = prefix_deltas.setdefault(model, {})
            outcome = labels.get("outcome", "")
            by[outcome] = by.get(outcome, 0.0) + delta
        key = self.store.key(name, labels)
        live.add(key)
        return 1 if self.store.record(name, labels, "rate", now,
                                      delta / dt) else 0

    def _sample_histogram(self, reg, name, labels, child, now, dt,
                          live, seen_baselines) -> int:
        base_key = (id(reg), name, tuple(sorted(labels.items())))
        seen_baselines.add(base_key)
        with child._lock:
            counts = list(child.counts)
            total = child.total
        prev = self._prev_hist.get(base_key)
        self._prev_hist[base_key] = (counts, total)
        # Derived series stay live while their source child exists
        # (idle histograms keep stale-but-served quantile rings).
        for _, suffix in QUANTILES:
            live.add(self.store.key(name + suffix, labels))
        live.add(self.store.key(name + "_count", labels))
        if prev is None:
            return 0
        prev_counts, prev_total = prev
        if total < prev_total or len(prev_counts) != len(counts):
            prev_counts, prev_total = [0] * len(counts), 0  # reset
        d_total = total - prev_total
        points = 0
        if self.store.record(name + "_count", labels, "rate", now,
                             d_total / dt):
            points += 1
        if d_total <= 0:
            return points  # no new observations: quantiles get a gap
        d_counts = [a - b for a, b in zip(counts, prev_counts)]
        for q, suffix in QUANTILES:
            value = _quantile(child.buckets, d_counts, d_total, q)
            if self.store.record(name + suffix, labels, "quantile",
                                 now, value):
                points += 1
        return points

    def _synthetic_ratios(self, now, live, request_deltas,
                          prefix_deltas) -> int:
        points = 0
        for model, by in request_deltas.items():
            seen = by.get("ok", 0.0) + by.get("error", 0.0)
            key = self.store.key(ERROR_RATIO_SERIES,
                                 {"model": model})
            live.add(key)
            if seen <= 0:
                continue  # idle: keep the ring, record nothing
            if self.store.record(ERROR_RATIO_SERIES,
                                 {"model": model}, "ratio", now,
                                 by.get("error", 0.0) / seen):
                points += 1
        for model, by in prefix_deltas.items():
            lookups = sum(by.values())
            key = self.store.key(PREFIX_HIT_RATIO_SERIES,
                                 {"model": model})
            live.add(key)
            if lookups <= 0:
                continue
            hits = by.get("hit", 0.0) + by.get("host_hit", 0.0)
            if self.store.record(PREFIX_HIT_RATIO_SERIES,
                                 {"model": model}, "ratio", now,
                                 hits / lookups):
                points += 1
        return points
