"""Flagship TPU model zoo.

The reference serves models as opaque artifacts executed by third-party
servers (TFServing/Triton/torchserve — reference
pkg/apis/serving/v1beta1/predictor.go:33-59); it ships no model code.  The
TPU-native build instead ships first-party Flax implementations of the
BASELINE.json benchmark configs so the jaxserver predictor runtime has real
compiled graphs to serve:

- resnet:   ResNet-50 v1.5 image classifier (flagship bench config #2)
- bert:     BERT-base fill-mask (seq-len bucketed batching, config #3)
- vit:      ViT-B/16 image classifier (config #5)
- mlp:      small MLPs for multi-model hot-swap serving (config #4)

All models follow the same convention: a `flax.linen.Module` plus a
`create_<name>()` helper returning `(module, example_input)` so the engine,
graft entry, and tests share one construction path.  Compute dtype defaults
to bfloat16 on TPU (MXU-native) with float32 params.
"""

from kfserving_tpu.models.registry import (  # noqa: F401
    ModelSpec,
    apply_fn_for,
    create_model,
    init_params,
    list_models,
    register_model,
)
