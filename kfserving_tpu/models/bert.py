"""BERT encoder with an MLM head — the seq-len-bucketed serving config.

BASELINE.json config #3: "jaxserver BERT-base fill-mask (seq-len bucketed
batching)".  First-party Flax implementation (the reference ships no model
code, SURVEY.md §2.2).

TPU notes:
- attention dispatches through kfserving_tpu.ops.dot_product_attention, so
  long-sequence buckets hit the Pallas flash kernel;
- seq-len is a compile-time shape: the engine's seq BucketPolicy pads token
  batches to bucket boundaries (multiples of 128 — MXU/VPU lane friendly);
- padding tokens are masked via attention_mask, so bucket padding never
  leaks into real logits;
- MLM head ties the embedding matrix (standard BERT weight tying) — one
  fewer [vocab, hidden] tensor in HBM.
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from kfserving_tpu.ops import dot_product_attention


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_layers=12, num_heads=12, intermediate_size=3072,
                 max_position=512, type_vocab_size=2,
                 layer_norm_eps=1e-12, dtype=jnp.bfloat16,
                 gelu_approximate=True, prefix_padding=True,
                 attn_fn=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.layer_norm_eps = layer_norm_eps
        self.dtype = dtype
        # tanh-approx gelu is the TPU default; checkpoints converted
        # from HF torch BERT ("gelu" = erf) set False for exact parity.
        self.gelu_approximate = gelu_approximate
        # attention_mask is treated as suffix key padding (1s then 0s —
        # what the serving batcher produces), which unlocks the
        # padding-aware flash kernel.  Set False to serve arbitrary
        # mask patterns through the XLA path.
        self.prefix_padding = prefix_padding
        # Pluggable attention impl (q, k, v, mask) -> out, mask being the
        # broadcastable [B, 1, 1, L] key-padding mask (or None).  Defaults
        # to ops.dot_product_attention; the sequence-parallel serving
        # config injects parallel.ring_attention_sharded(mesh).
        self.attn_fn = attn_fn


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, mask):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads

        def proj(name):
            return nn.DenseGeneral(
                (cfg.num_heads, head_dim), dtype=cfg.dtype, name=name)

        q = proj("query")(hidden)          # [B, L, H, D]
        k = proj("key")(hidden)
        v = proj("value")(hidden)
        # mask [B, L] -> [B, 1, 1, L] broadcast over heads and query pos.
        attn_mask = None
        if mask is not None:
            attn_mask = mask[:, None, None, :].astype(bool)
        if cfg.attn_fn is not None:
            out = cfg.attn_fn(q, k, v, attn_mask)
        else:
            # prefix_padding declares serving masks to be suffix padding
            # (the batcher pads seq buckets at the end): the flash
            # kernel consumes the mask as per-row lengths, while the
            # XLA fallback applies the true mask — a direct caller with
            # an interior mask stays correct on XLA (suffix-ness is
            # enforced host-side for serving by
            # jax_model._check_prefix_mask).
            out = dot_product_attention(
                q, k, v, mask=attn_mask,
                prefix_padding=cfg.prefix_padding)
        out = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype, name="out")(out)
        return out


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, mask):
        cfg = self.config
        attn = BertSelfAttention(cfg, name="attention")(hidden, mask)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              name="attention_norm")(hidden + attn)
        mlp = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                       name="intermediate")(hidden)
        mlp = nn.gelu(mlp, approximate=cfg.gelu_approximate)
        mlp = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="output")(mlp)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            name="output_norm")(hidden + mlp)


class BertForMaskedLM(nn.Module):
    """Token ids -> MLM logits.  Inputs: input_ids [B, L] int32, optional
    attention_mask [B, L] (1 = real token)."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask: Optional[Any] = None,
                 token_type_ids: Optional[Any] = None):
        cfg = self.config
        B, L = input_ids.shape
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         dtype=cfg.dtype, name="word_embeddings")
        hidden = embed(input_ids)
        positions = jnp.arange(L)[None, :]
        hidden += nn.Embed(cfg.max_position, cfg.hidden_size, dtype=cfg.dtype,
                           name="position_embeddings")(positions)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        hidden += nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                           dtype=cfg.dtype,
                           name="token_type_embeddings")(token_type_ids)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              name="embeddings_norm")(hidden)
        for i in range(cfg.num_layers):
            hidden = BertLayer(cfg, name=f"layer_{i}")(hidden, attention_mask)
        # MLM head: transform + tied-embedding decoder.
        hidden = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                          name="mlm_transform")(hidden)
        hidden = nn.gelu(hidden, approximate=cfg.gelu_approximate)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              name="mlm_norm")(hidden)
        logits = embed.attend(hidden.astype(embed.embedding.dtype))
        logits += self.param("mlm_bias", nn.initializers.zeros,
                             (cfg.vocab_size,), jnp.float32)
        return logits.astype(jnp.float32)


def bert_base(**overrides):
    return BertConfig(**overrides)


def bert_tiny(**overrides):
    """4-layer/128-wide config for hermetic CPU tests."""
    defaults = dict(vocab_size=1024, hidden_size=128, num_layers=4,
                    num_heads=4, intermediate_size=512, max_position=512)
    defaults.update(overrides)
    return BertConfig(**defaults)


def create_bert(config: Optional[BertConfig] = None, seq_len: int = 128):
    """Returns (module, example_inputs dict)."""
    cfg = config or bert_base()
    module = BertForMaskedLM(cfg)
    example = {
        "input_ids": jnp.zeros((1, seq_len), jnp.int32),
        "attention_mask": jnp.ones((1, seq_len), jnp.int32),
    }
    return module, example


def _create_bert_base(**kw):
    """Registry factory: 'bert'."""
    seq_len = kw.pop("seq_len", 128)
    return create_bert(bert_base(**kw) if kw else None, seq_len=seq_len)


def _create_bert_tiny(seq_len=128, **kw):
    """Registry factory: 'bert_tiny'."""
    return create_bert(bert_tiny(**kw), seq_len=seq_len)
