"""Architecture registry: name -> (module, example_input) factories.

Plays the role of the reference's per-framework predictor dispatch
(reference pkg/apis/serving/v1beta1/predictor.go:33-59 picks a server image
by framework name): here the "framework" is an architecture string in the
model's config, and the factory yields a Flax module the JaxEngine can
compile.  Registration is open — user models plug in with
`register_model("myarch", factory)` exactly like custom predictors do in the
reference (predictor_custom.go).
"""

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax


class ModelSpec(NamedTuple):
    module: Any                # flax.linen.Module
    example: Any               # single-instance example input (batch dim 1)


_REGISTRY: Dict[str, Callable[..., Tuple[Any, Any]]] = {}


def register_model(name: str, factory: Callable[..., Tuple[Any, Any]]):
    _REGISTRY[name] = factory


def list_models():
    return sorted(_REGISTRY)


def create_model(name: str, **kwargs) -> ModelSpec:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown architecture {name!r}; known: {list_models()}")
    module, example = _REGISTRY[name](**kwargs)
    return ModelSpec(module, example)


def init_params(spec: ModelSpec, seed: int = 0):
    """Initialize variables for a ModelSpec (random weights — serving tests
    and benchmarks measure compute, not accuracy)."""
    rng = jax.random.PRNGKey(seed)
    example = spec.example
    if isinstance(example, dict):
        return spec.module.init(rng, **example)
    return spec.module.init(rng, example)


def apply_fn_for(spec: ModelSpec) -> Callable:
    """A (variables, batch) -> output function in the JaxEngine calling
    convention (engine/jax_engine.py:34-44): dict inputs are splatted as
    kwargs, array inputs positionally."""
    module = spec.module
    if isinstance(spec.example, dict):
        def apply(variables, batch):
            return module.apply(variables, **batch)
    else:
        def apply(variables, batch):
            return module.apply(variables, batch)
    return apply


def _register_builtins():
    from kfserving_tpu.models import bert, mlp, resnet, vit

    register_model("resnet50", resnet.create_resnet50)
    register_model("bert", lambda **kw: bert.create_bert(**kw))
    register_model(
        "bert_tiny",
        lambda seq_len=128, **kw: bert.create_bert(
            bert.bert_tiny(**kw), seq_len=seq_len))
    register_model("vit_b16", lambda **kw: vit.create_vit(
        vit.vit_b16(**kw)))
    register_model("vit_tiny", lambda **kw: vit.create_vit(
        vit.vit_tiny(**kw)))
    register_model("mlp", mlp.create_mlp)


_register_builtins()
