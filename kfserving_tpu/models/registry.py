"""Architecture registry: name -> (module, example_input) factories.

Plays the role of the reference's per-framework predictor dispatch
(reference pkg/apis/serving/v1beta1/predictor.go:33-59 picks a server image
by framework name): here the "framework" is an architecture string in the
model's config, and the factory yields a Flax module the JaxEngine can
compile.  Registration is open — user models plug in with
`register_model("myarch", factory)` exactly like custom predictors do in the
reference (predictor_custom.go).
"""

from typing import Any, Callable, Dict, NamedTuple, Tuple


class ModelSpec(NamedTuple):
    module: Any                # flax.linen.Module
    example: Any               # single-instance example input (batch dim 1)


_REGISTRY: Dict[str, Callable[..., Tuple[Any, Any]]] = {}

# Built-ins resolve lazily (module_path, builder_name) so importing the
# registry — e.g. control-plane code listing architectures — doesn't pay
# jax/flax initialization.  The model modules import on first create_model.
_LAZY_BUILTINS: Dict[str, Tuple[str, str]] = {
    "resnet50": ("kfserving_tpu.models.resnet", "create_resnet50"),
    "bert": ("kfserving_tpu.models.bert", "_create_bert_base"),
    "bert_tiny": ("kfserving_tpu.models.bert", "_create_bert_tiny"),
    "vit_b16": ("kfserving_tpu.models.vit", "_create_vit_b16"),
    "vit_tiny": ("kfserving_tpu.models.vit", "_create_vit_tiny"),
    "mlp": ("kfserving_tpu.models.mlp", "create_mlp"),
    "decoder": ("kfserving_tpu.models.decoder", "_create_decoder_small"),
    "decoder_tiny": ("kfserving_tpu.models.decoder",
                     "_create_decoder_tiny"),
}


def register_model(name: str, factory: Callable[..., Tuple[Any, Any]]):
    _REGISTRY[name] = factory


def list_models():
    return sorted(set(_REGISTRY) | set(_LAZY_BUILTINS))


def _resolve(name: str) -> Callable[..., Tuple[Any, Any]]:
    if name in _REGISTRY:
        return _REGISTRY[name]
    if name in _LAZY_BUILTINS:
        import importlib

        module_path, attr = _LAZY_BUILTINS[name]
        factory = getattr(importlib.import_module(module_path), attr)
        _REGISTRY[name] = factory
        return factory
    raise KeyError(
        f"unknown architecture {name!r}; known: {list_models()}")


def create_model(name: str, **kwargs) -> ModelSpec:
    module, example = _resolve(name)(**kwargs)
    return ModelSpec(module, example)


def init_params(spec: ModelSpec, seed: int = 0):
    """Initialize variables for a ModelSpec (random weights — serving tests
    and benchmarks measure compute, not accuracy).

    The init runs under jit: eager flax init dispatches one device op
    per parameter, which on a tunneled chip is hundreds of ~100ms round
    trips (measured 13s for ResNet-50 — the dominant term of the r3
    recycle brownout).  Jitted, it is one compiled program (persistent-
    cache-hot on respawn) and one execution."""
    import jax

    rng = jax.random.PRNGKey(seed)
    example = spec.example
    if isinstance(example, dict):
        init = jax.jit(lambda r: spec.module.init(r, **example))
    else:
        init = jax.jit(lambda r: spec.module.init(r, example))
    return init(rng)


def apply_fn_for(spec: ModelSpec) -> Callable:
    """A (variables, batch) -> output function in the JaxEngine calling
    convention (engine/jax_engine.py:34-44): dict batches are splatted as
    kwargs, array batches positionally.

    Dispatch is on the *runtime* batch type, not the example's: a
    dict-example model (e.g. BERT with optional attention_mask) must
    still accept a bare array when a V1 request carries only the primary
    input — the array binds to the module's first positional arg.  The
    isinstance check is static under jit tracing (it runs once per
    compiled signature)."""
    module = spec.module

    def apply(variables, batch):
        if isinstance(batch, dict):
            return module.apply(variables, **batch)
        return module.apply(variables, batch)
    return apply


