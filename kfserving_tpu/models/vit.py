"""ViT-B/16 in Flax — the transformer->predictor chain config.

BASELINE.json config #5: "transformer->predictor chain: pre-process pod +
jaxserver ViT-B/16 on v5e-4".  The v5e-4 part matters: ViT-B is the model
used to exercise within-replica tensor parallelism (kfserving_tpu.parallel),
so its MLP/attention dims are chosen to shard cleanly over a tp axis.

Patch embedding is a conv with stride=patch (one MXU GEMM over unfolded
patches under XLA); encoder blocks share the ops.dot_product_attention
dispatch with BERT.
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from kfserving_tpu.ops import dot_product_attention


class ViTConfig:
    def __init__(self, image_size=224, patch_size=16, hidden_size=768,
                 num_layers=12, num_heads=12, intermediate_size=3072,
                 num_classes=1000, dtype=jnp.bfloat16):
        self.image_size = image_size
        self.patch_size = patch_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.num_classes = num_classes
        self.dtype = dtype


class EncoderBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        y = nn.LayerNorm(dtype=cfg.dtype, name="norm1")(x)
        q = nn.DenseGeneral((cfg.num_heads, head_dim), dtype=cfg.dtype,
                            name="query")(y)
        k = nn.DenseGeneral((cfg.num_heads, head_dim), dtype=cfg.dtype,
                            name="key")(y)
        v = nn.DenseGeneral((cfg.num_heads, head_dim), dtype=cfg.dtype,
                            name="value")(y)
        attn = dot_product_attention(q, k, v)
        attn = nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, name="out")(attn)
        x = x + attn
        y = nn.LayerNorm(dtype=cfg.dtype, name="norm2")(x)
        y = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="mlp_in")(y)
        y = nn.gelu(y, approximate=True)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(y)
        return x + y


class ViT(nn.Module):
    """Images [B, H, W, 3] float -> class logits [B, num_classes]."""

    config: ViTConfig

    @nn.compact
    def __call__(self, images):
        cfg = self.config
        x = images.astype(cfg.dtype)
        p = cfg.patch_size
        x = nn.Conv(cfg.hidden_size, (p, p), strides=(p, p),
                    padding="VALID", dtype=cfg.dtype, name="patch_embed")(x)
        B, h, w, c = x.shape
        x = x.reshape(B, h * w, c)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.hidden_size), jnp.float32)
        x = jnp.concatenate(
            [jnp.tile(cls.astype(cfg.dtype), (B, 1, 1)), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(0.02),
                         (1, h * w + 1, cfg.hidden_size), jnp.float32)
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = EncoderBlock(cfg, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_norm")(x)
        # Classify from the CLS token, head in float32.
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        name="head")(x[:, 0])


def vit_b16(**overrides):
    return ViTConfig(**overrides)


def vit_tiny(**overrides):
    defaults = dict(image_size=32, patch_size=8, hidden_size=64,
                    num_layers=2, num_heads=4, intermediate_size=128,
                    num_classes=10)
    defaults.update(overrides)
    return ViTConfig(**defaults)


def create_vit(config: Optional[ViTConfig] = None):
    cfg = config or vit_b16()
    module = ViT(cfg)
    example = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
    return module, example


def _create_vit_b16(**kw):
    """Registry factory: 'vit_b16'."""
    return create_vit(vit_b16(**kw))


def _create_vit_tiny(**kw):
    """Registry factory: 'vit_tiny'."""
    return create_vit(vit_tiny(**kw))
