"""Decoder-only transformer (GPT-class) with KV-cache serving modes.

The reference treats generative models as opaque request/response
artifacts behind the same predict route as everything else (reference
pkg/apis/serving/v1beta1/predictor.go:33-59 — no decoder-aware serving
exists anywhere in it).  A TPU-native serving framework needs the
decoder to be a first-class citizen: incremental decoding with a KV
cache is what makes generation O(L) instead of O(L^2), and the cache
layout decides whether the decode step maps onto the MXU.

One Flax module, three executions (all static-shape, jit-friendly):

- **full**: `input_ids [B, L] -> logits [B, L, V]` — causal attention
  over the whole sequence.  Teacher-forcing / parity baseline.
- **prefill**: same forward pass with `return_cache=True` — also
  returns every layer's (k, v) [B, L, H, D] so the serving engine can
  scatter them into slot caches.  Suffix padding is masked via
  `kv_lengths` and rides the padding-aware flash kernel at long L.
- **decode**: `input_ids [B, 1]` with `kv_cache` — writes the step's
  k/v into the caches at per-row `positions` (one scatter per layer)
  and attends over the valid prefix.  B here is the engine's slot
  count: one compiled program serves continuous batching forever.

TPU notes:
- pre-LN blocks (GPT-2 style): the residual stream stays bf16; logits
  come back float32 for stable sampling.
- the LM head ties the embedding matrix (one [V, H] tensor in HBM).
- caches are [B, max_seq, H, D] per layer — sequence-major so the
  decode attention reads are contiguous along the lane dimension, and
  the slot axis (B) is shardable for tensor parallelism on heads.
- attention dispatches through ops.dot_product_attention: causal
  full/prefill hits the flash kernel when eligible; decode's
  Lq=1 masked read is a skinny matmul XLA fuses well.
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from kfserving_tpu.ops import dot_product_attention


class DecoderConfig:
    def __init__(self, vocab_size=32000, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072, max_seq=1024,
                 layer_norm_eps=1e-5, dtype=jnp.bfloat16,
                 attn_fn=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_seq = max_seq
        self.layer_norm_eps = layer_norm_eps
        self.dtype = dtype
        # Pluggable full/prefill attention (q, k, v, mask) -> out for
        # sequence-parallel serving (ring attention), mirroring
        # models/bert.py.  Decode-mode cache attention is not pluggable:
        # its Lq=1 reads are latency-bound, not sequence-shardable.
        self.attn_fn = attn_fn

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


class DecoderBlock(nn.Module):
    config: DecoderConfig

    @nn.compact
    def __call__(self, hidden, *, mask=None, kv_lengths=None,
                 cache=None, positions=None):
        """cache: optional (k_cache, v_cache) [B, max_seq, H, D] pair —
        decode mode.  positions: [B] absolute position of the current
        token (decode) — the scatter index for the cache write."""
        cfg = self.config
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="attn_norm")(hidden)

        def proj(name):
            return nn.DenseGeneral((cfg.num_heads, cfg.head_dim),
                                   dtype=cfg.dtype, name=name)

        q = proj("query")(x)
        k = proj("key")(x)
        v = proj("value")(x)
        lq = q.shape[1]
        new_cache = None
        if cache is not None and len(cache) == 3:
            # Paged cache: cache = (pool_k, pool_v, block_table) —
            # shared block pools [NB, BS, H, D] plus this batch's
            # [B, MB] table (engine/generator.py paged mode; the
            # static 3-vs-2 tuple arity picks the branch at trace
            # time).  The table flows in per dispatch and is not
            # returned — only the written pools are.  Lq == 1 is the
            # decode step; Lq > 1 is a CHUNK PREFILL: the chunk's
            # tokens write through the table, then attend over the
            # pool with per-query causal masking (earlier chunks are
            # already resident — cross-chunk attention comes from the
            # pool, exactly like decode).
            from kfserving_tpu.ops.paged_attention import (
                paged_attention,
                paged_prefill_attention_xla,
                paged_write,
            )

            pool_k, pool_v, table = cache
            if lq == 1:
                pool_k, pool_v = paged_write(pool_k, pool_v, k[:, 0],
                                             v[:, 0], table,
                                             positions[:, 0])
                new_cache = (pool_k, pool_v)
                out = paged_attention(q, pool_k, pool_v, table,
                                      positions[:, 0] + 1)
            else:
                pool_k, pool_v = paged_write(pool_k, pool_v, k, v,
                                             table, positions)
                new_cache = (pool_k, pool_v)
                out = paged_prefill_attention_xla(q, pool_k, pool_v,
                                                  table, positions)
        elif cache is not None:
            k_cache, v_cache = cache
            b = k_cache.shape[0]
            rows = jnp.arange(b)[:, None]
            # mode="drop": positions carry an out-of-range sentinel
            # for rows the engine parked (freed / mid-prefill slots) —
            # a clamped write would corrupt the row's last position.
            k_cache = k_cache.at[rows, positions].set(
                k.astype(k_cache.dtype), mode="drop")
            v_cache = v_cache.at[rows, positions].set(
                v.astype(v_cache.dtype), mode="drop")
            new_cache = (k_cache, v_cache)
            # Valid keys are exactly positions <= the query's own
            # position (per query — Lq > 1 is a chunk prefill).
            max_seq = k_cache.shape[1]
            attn_mask = (jnp.arange(max_seq)[None, None, :]
                         <= positions[:, :, None])[:, None]
            out = dot_product_attention(q, k_cache, v_cache,
                                        mask=attn_mask)
        elif cfg.attn_fn is not None:
            attn_mask = None
            lq = q.shape[1]
            causal = jnp.tril(jnp.ones((lq, lq), jnp.bool_))[None, None]
            if kv_lengths is not None:
                pad = (jnp.arange(lq)[None, :]
                       < kv_lengths[:, None])[:, None, None, :]
                attn_mask = causal & pad
            else:
                attn_mask = causal
            out = cfg.attn_fn(q, k, v, attn_mask)
            # The k/v projections are already materialized; without
            # this a prefill with return_cache=True under a pluggable
            # attn_fn returned caches=[None, ...] and crashed deep in
            # the engine's insert scatter instead of working.
            new_cache = (k, v)
        else:
            out = dot_product_attention(q, k, v, causal=True,
                                        kv_lengths=kv_lengths)
            new_cache = (k, v)
        out = nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1),
                              dtype=cfg.dtype, name="out")(out)
        hidden = hidden + out
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="mlp_norm")(hidden)
        x = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     name="mlp_in")(x)
        x = nn.gelu(x, approximate=True)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(x)
        return hidden + x, new_cache


class DecoderLM(nn.Module):
    """Token ids -> next-token logits, with optional KV-cache modes.

    full/prefill: input_ids [B, L]; kv_lengths optional [B] (suffix
        real-token counts — bucket padding).  Returns logits [B, L, V]
        (float32), plus per-layer (k, v) [B, L, H, D] when
        return_cache=True.
    decode: input_ids [B, 1] + kv_cache (list of per-layer (k, v)
        [B, max_seq, H, D]) + positions [B].  Returns logits [B, 1, V]
        and the updated caches.
    chunk prefill: input_ids [B, L>1] + kv_cache + positions [B, L] —
        the chunk's tokens write into the cache at their absolute
        positions and attend per-query-causally over the cache
        (earlier chunks included), so a long prompt lands in
        block-aligned pieces between decode waves.
    logit_positions: optional [B] or [B, P] int32 — compute logits
        ONLY at those positions per row (hidden gathered before the
        final norm + LM head).  The sampled-token path never needs
        the [B, L, V] logits cube; skipping it drops the LM-head
        matmul from O(L·H·V) to O(P·H·V) per row, the dominant
        prefill FLOP at long L.  [B] returns logits [B, 1, V]
        (chunked prefill's last-token slice); [B, P] returns
        [B, P, V] — speculative decoding's verify dispatch reads all
        K+1 positions of a draft run from the one Lq>1 forward.
    """

    config: DecoderConfig

    @nn.compact
    def __call__(self, input_ids, positions: Optional[Any] = None,
                 kv_cache: Optional[Any] = None,
                 kv_lengths: Optional[Any] = None,
                 return_cache: bool = False,
                 logit_positions: Optional[Any] = None):
        cfg = self.config
        b, l = input_ids.shape
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         dtype=cfg.dtype, name="wte")
        if positions is None:
            pos = jnp.arange(l)[None, :]
        else:
            pos = positions.reshape(b, -1)
        hidden = embed(input_ids)
        # Clamp for the position table: cache-mode callers park
        # padding/sentinel rows on max_seq (their cache writes drop;
        # an unclamped index would still be gather-clamped inside jit,
        # this just makes the contract explicit).
        hidden += nn.Embed(cfg.max_seq, cfg.hidden_size, dtype=cfg.dtype,
                           name="wpe")(jnp.minimum(pos, cfg.max_seq - 1))
        caches = []
        for i in range(cfg.num_layers):
            layer_cache = None if kv_cache is None else kv_cache[i]
            layer_pos = (None if kv_cache is None
                         else pos.reshape(b, -1))
            hidden, new_cache = DecoderBlock(cfg, name=f"layer_{i}")(
                hidden, kv_lengths=kv_lengths, cache=layer_cache,
                positions=layer_pos)
            caches.append(new_cache)
        if logit_positions is not None:
            # Per-row gather BEFORE the norm + LM head: LayerNorm and
            # the tied-embedding matmul are per-position, so the
            # sliced path is numerically identical to slicing the
            # full logits cube at the same indices.  reshape(b, -1, 1)
            # accepts both the [B] single-slice form and the [B, P]
            # multi-position form (speculative verify).
            hidden = jnp.take_along_axis(
                hidden, logit_positions.reshape(b, -1, 1), axis=1)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              name="final_norm")(hidden)
        logits = embed.attend(hidden.astype(embed.embedding.dtype))
        logits = logits.astype(jnp.float32)
        if kv_cache is not None:
            return logits, caches
        if return_cache:
            return logits, caches
        return logits


def decoder_small(**overrides):
    """GPT-2-small-class config (124M at vocab 50257)."""
    defaults = dict(vocab_size=50257, hidden_size=768, num_layers=12,
                    num_heads=12, intermediate_size=3072, max_seq=1024)
    defaults.update(overrides)
    return DecoderConfig(**defaults)


def decoder_tiny(**overrides):
    """4-layer/128-wide config for hermetic CPU tests.  vocab 384
    covers the byte tokenizer (258 ids) rounded up to a lane-friendly
    multiple of 128."""
    defaults = dict(vocab_size=384, hidden_size=128, num_layers=4,
                    num_heads=4, intermediate_size=512, max_seq=256,
                    dtype=jnp.float32)
    defaults.update(overrides)
    return DecoderConfig(**defaults)


def create_decoder(config: Optional[DecoderConfig] = None,
                   seq_len: int = 64):
    cfg = config or decoder_small()
    module = DecoderLM(cfg)
    example = jnp.zeros((1, seq_len), jnp.int32)
    return module, example


def _create_decoder_small(**kw):
    """Registry factory: 'decoder'."""
    seq_len = kw.pop("seq_len", 64)
    return create_decoder(decoder_small(**kw) if kw else None,
                          seq_len=seq_len)


def _create_decoder_tiny(seq_len=32, **kw):
    """Registry factory: 'decoder_tiny'."""
    return create_decoder(decoder_tiny(**kw), seq_len=seq_len)
