"""ResNet v1.5 in Flax — the flagship image-classification predictor.

BASELINE.json config #2: "jaxserver ResNet-50 image classify (dynamic batch,
v5e-1)".  The reference has no model code (it serves opaque artifacts,
SURVEY.md §2.2); this is a first-party TPU-native implementation.

TPU notes:
- NHWC layout: XLA's TPU conv emitter wants channels-last; the MXU tiles
  the implicit GEMMs of the convolutions.
- bfloat16 compute / float32 params ("mixed precision" without a loss
  scale — inference only needs the cast on the way in).
- BatchNorm folded to inference mode (use_running_average=True) so the whole
  forward pass is a pure function of (params, batch_stats, x) and fuses.
"""

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (v1.5: stride
    on the 3x3, which is what torchvision/TF reference models converged on)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    pad3: Any = "SAME"  # 3x3 conv padding (torch ckpts need explicit 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides,
                      padding=self.pad3)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: identity-at-init residual branches
        # (standard ResNet trick; keeps early logits sane for warmup probes).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj")(
                    residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5.  stage_sizes [3,4,6,3] == ResNet-50."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    # torch checkpoints were trained with explicit (3,3)/(1,1) conv pads
    # and a padded max_pool; "SAME" puts the asymmetric pad on the other
    # side at even sizes, shifting every stride-2 conv by one pixel.
    # Serving converted weights needs the torch geometry.
    torch_padding: bool = False

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=True,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        act = nn.relu
        pad7 = ((3, 3), (3, 3)) if self.torch_padding else "SAME"
        pad3 = ((1, 1), (1, 1)) if self.torch_padding else "SAME"
        pool_pad = ((1, 1), (1, 1)) if self.torch_padding else "SAME"

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=pad7,
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=pool_pad)
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    self.num_filters * 2 ** i, strides=strides,
                    conv=conv, norm=norm, act=act, pad3=pad3)(x)
        x = jnp.mean(x, axis=(1, 2))
        # Head in float32: logits feed softmax/argmax on host.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2])   # (uses bottleneck too;
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])   # serving zoo, not a
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])  # training repro)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])


def create_resnet50(num_classes: int = 1000, image_size: int = 224,
                    dtype: Any = jnp.bfloat16,
                    torch_padding: bool = False):
    """Returns (module, example_input[1, H, W, 3])."""
    module = ResNet50(num_classes=num_classes, dtype=dtype,
                      torch_padding=torch_padding)
    example = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    return module, example
