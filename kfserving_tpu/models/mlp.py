"""Small MLPs for multi-model serving.

BASELINE.json config #4: "multi-model serving: 8 small Flax MLPs hot-swapped
via pkg/agent on one chip".  These are the TrainedModel-equivalent payloads:
cheap to load/unload, with a declared HBM footprint that exercises the
HBM-aware sharding strategy (control plane) and the engine's eviction
accounting (engine/hbm.py) — the reference's `Memory` field made real
(reference pkg/apis/serving/v1alpha1/trained_model.go:68-69).
"""

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int]
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = x.reshape(x.shape[0], -1)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def create_mlp(input_dim: int = 64, features: Sequence[int] = (256, 256),
               num_classes: int = 10):
    module = MLP(features=tuple(features), num_classes=num_classes)
    example = jnp.zeros((1, input_dim), jnp.float32)
    return module, example
