"""Paged KV-cache attention for single-token decode.

The dense slot pool ([S, max_seq, H, D] per layer) burns the same HBM
for a 40-token chat as for a full-context one (VERDICT r4 weak #5).
Paging replaces it with a shared block pool ([num_blocks, block_size,
H, D]) plus a per-slot block table — HBM scales with tokens actually
resident, and identical prompt prefixes can share blocks (prefix
reuse).  This is the TPU analogue of vLLM's PagedAttention; the
reference has no serving-cache concept at all (its `Memory` field is
a k8s resource quantity, reference
pkg/apis/serving/v1alpha1/trained_model.go:68-69).

Two implementations with one contract:

- `paged_attention_xla`: gather the slot's blocks into a contiguous
  [B, MB*BS, H, D] view and run masked attention.  Compiles anywhere
  (the hermetic CPU tests run it), but materializes the gathered copy
  every step.
- a Pallas TPU kernel (paged_attention_tpu) that walks the block
  table with scalar prefetch and never materializes — only blocks
  holding valid tokens are read, so a short sequence in a long-context
  pool costs its length, not the pool width.  (Added when measured;
  the dispatcher falls back to XLA.)

Contract (per layer):
    q           [B, 1, H, D]   current step's query
    pool_k/v    [NB, BS, H, D] shared block pools
    block_table [B, MB] int32  block ids per slot, -1 = unallocated
    lengths     [B] int32      valid tokens INCLUDING the current
                               step's write
Returns [B, 1, H, D].
"""

import jax
import jax.numpy as jnp


def paged_attention_xla(q, pool_k, pool_v, block_table, lengths):
    b, lq, h, d = q.shape
    nb, bs, _, _ = pool_k.shape
    mb = block_table.shape[1]
    # Clamp -1 (unallocated) to 0: masked out below, and XLA's gather
    # clamps anyway — explicit is better than relying on OOB behavior.
    table = jnp.maximum(block_table, 0)
    # [B, MB, BS, H, D] -> [B, MB*BS, H, D]
    k = pool_k[table].reshape(b, mb * bs, h, d)
    v = pool_v[table].reshape(b, mb * bs, h, d)
    positions = jnp.arange(mb * bs)[None, :]
    mask = (positions < lengths[:, None])[:, None, None, :]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights,
                     v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_write(pool_k, pool_v, k_step, v_step, block_table,
                positions):
    """Scatter one decode step's k/v ([B, H, D] each) into the pools
    at each slot's current position.  Unallocated targets (-1 in the
    table) drop via OOB sentinel."""
    bs = pool_k.shape[1]
    block_idx = positions // bs
    offs = positions % bs
    rows = jnp.arange(block_table.shape[0])
    blocks = block_table[rows, jnp.minimum(block_idx,
                                           block_table.shape[1] - 1)]
    # -1 -> OOB sentinel so mode="drop" discards the write.
    blocks = jnp.where(blocks < 0, pool_k.shape[0], blocks)
    pool_k = pool_k.at[blocks, offs].set(
        k_step.astype(pool_k.dtype), mode="drop")
    pool_v = pool_v.at[blocks, offs].set(
        v_step.astype(pool_v.dtype), mode="drop")
    return pool_k, pool_v


def paged_insert(pool_k, pool_v, k_new, v_new, dest_blocks, lengths):
    """Insert a prefill batch's k/v ([B, L, H, D]) into pool blocks.

    dest_blocks [B, ceil(L/BS)] int32: destination block id per
    L-chunk of each row; -1 chunks drop (bucket padding rows, or
    prefix-cache hits whose blocks already hold the data).  Positions
    beyond lengths[i] within a written block are harmless garbage —
    reads mask by length."""
    b, l, h, d = k_new.shape
    bs = pool_k.shape[1]
    chunks = l // bs
    assert chunks * bs == l, "prefill bucket must be block-aligned"
    dest = jnp.where(dest_blocks < 0, pool_k.shape[0], dest_blocks)
    k_c = k_new.reshape(b * chunks, bs, h, d)
    v_c = v_new.reshape(b * chunks, bs, h, d)
    flat_dest = dest.reshape(b * chunks)
    pool_k = pool_k.at[flat_dest].set(k_c.astype(pool_k.dtype),
                                      mode="drop")
    pool_v = pool_v.at[flat_dest].set(v_c.astype(pool_v.dtype),
                                      mode="drop")
    return pool_k, pool_v
