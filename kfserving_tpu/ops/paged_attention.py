"""Paged KV-cache attention for single-token decode.

The dense slot pool ([S, max_seq, H, D] per layer) burns the same HBM
for a 40-token chat as for a full-context one (VERDICT r4 weak #5).
Paging replaces it with a shared block pool ([num_blocks, block_size,
H, D]) plus a per-slot block table — HBM scales with tokens actually
resident, and identical prompt prefixes can share blocks (prefix
reuse).  This is the TPU analogue of vLLM's PagedAttention; the
reference has no serving-cache concept at all (its `Memory` field is
a k8s resource quantity, reference
pkg/apis/serving/v1alpha1/trained_model.go:68-69).

Two implementations with one contract:

- `paged_attention_xla`: gather the slot's blocks into a contiguous
  [B, MB*BS, H, D] view and run masked attention.  Compiles anywhere
  (the hermetic CPU tests run it), but materializes the gathered copy
  every step.
- a Pallas TPU kernel (paged_attention_tpu) that walks the block
  table with scalar prefetch and never materializes — only blocks
  holding valid tokens are read, so a short sequence in a long-context
  pool costs its length, not the pool width.  (Added when measured;
  the dispatcher falls back to XLA.)

Contract (per layer):
    q           [B, 1, H, D]   current step's query
    pool_k/v    [NB, BS, H, D] shared block pools
    block_table [B, MB] int32  block ids per slot, -1 = unallocated
    lengths     [B] int32      valid tokens INCLUDING the current
                               step's write
Returns [B, 1, H, D].
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _paged_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scratch, l_scratch, acc_scratch, *,
                  block_size: int, scale: float, num_heads: int):
    """One batch row's online-softmax walk over its block table, all
    heads per program (head-batched dot_generals keep the block
    shapes' trailing dims equal to the array dims — Mosaic's tiling
    requirement).  Grid: (B, MB) with the block axis innermost and
    sequential; the index maps clamp the pool-block index so programs
    past a row's valid length re-DMA an already-resident block —
    invalid blocks cost neither HBM traffic nor FLOPs (the flash
    kernel's kv_lengths clamp, applied to a block table).  The
    gathered [B, MB*BS, H, D] view the XLA fallback materializes
    every step never exists here."""
    b_idx = pl.program_id(0)
    j_idx = pl.program_id(1)
    num_j = pl.num_programs(1)
    row_len = len_ref[b_idx]

    @pl.when(j_idx == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    h = num_heads

    def _run_block():
        # Decode attention is a per-head matvec — bandwidth-bound, so
        # everything here is VPU elementwise+reduce (Mosaic's in-kernel
        # dot does not take batched dimension numbers).  Scores keep
        # the [bs, h] orientation end-to-end: reductions run over the
        # major axis and no relayout-heavy transposes are needed.
        q = q_ref[0, 0].astype(jnp.float32)               # [h, d]
        k = k_ref[0].astype(jnp.float32)                  # [bs, h, d]
        s = jnp.sum(k * q[None], axis=-1) * scale         # [bs, h]
        pos = j_idx * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_size, h), 0)
        s = jnp.where(pos < row_len, s, _NEG_INF)
        m_prev = m_scratch[0:1, 0:h]                      # [1, h]
        l_prev = l_scratch[0:1, 0:h]
        m_cur = jnp.max(s, axis=0, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                            # [bs, h]
        alpha = jnp.exp(m_prev - m_new)                   # [1, h]
        l_new = alpha * l_prev + jnp.sum(p, axis=0, keepdims=True)
        v = v_ref[0].astype(jnp.float32)                  # [bs, h, d]
        pv = jnp.sum(p[:, :, None] * v, axis=0)           # [h, d]
        alpha_col = jnp.swapaxes(alpha, 0, 1)             # [h, 1]
        acc_scratch[0:h] = acc_scratch[0:h] * alpha_col + pv
        m_scratch[0:1, 0:h] = m_new
        l_scratch[0:1, 0:h] = l_new

    # Blocks wholly past the row's length never run.
    pl.when(j_idx * block_size < row_len)(_run_block)

    @pl.when(j_idx == num_j - 1)
    def _finalize():
        l_col = jnp.swapaxes(l_scratch[0:1, 0:h], 0, 1)   # [h, 1]
        o_ref[0, 0] = (acc_scratch[0:h]
                       / jnp.maximum(l_col, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_tpu(q, pool_k, pool_v, block_table, lengths,
                        interpret: bool = False):
    """Pallas paged decode attention — same contract as
    `paged_attention_xla`, without materializing the gathered cache
    view, and reading only blocks that hold valid tokens (a short
    sequence in a long-context pool costs its length, not the pool
    width)."""
    b, lq, h, d = q.shape
    nb, bs, _, _ = pool_k.shape
    mb = block_table.shape[1]
    scale = 1.0 / (d ** 0.5)
    table_flat = jnp.maximum(block_table, 0).reshape(-1)
    lengths = lengths.astype(jnp.int32)

    def q_index(bi, ji, table, lens):
        return (bi, 0, 0, 0)

    def kv_index(bi, ji, table, lens):
        # Clamp the walk to the row's last VALID table entry: programs
        # past the length re-address a resident block (no new DMA, and
        # pl.when skips their compute).
        last = jnp.maximum(
            jax.lax.div(lens[bi] - 1, jnp.int32(bs)), 0)
        jj = jnp.minimum(ji, last)
        return (table[bi * mb + jj], 0, 0, 0)

    # Stats scratch is lane-padded to 128 (Mosaic tiling); only
    # column 0 is used.
    h_pad = max(8, -(-h // 8) * 8)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((1, 1, h, d), q_index),
            pl.BlockSpec((1, bs, h, d), kv_index),
            pl.BlockSpec((1, bs, h, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, h, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((h_pad, 128), jnp.float32),
            pltpu.VMEM((h_pad, 128), jnp.float32),
            pltpu.VMEM((h_pad, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, block_size=bs,
                               scale=scale, num_heads=h)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, lq, h, d), q.dtype),
        interpret=interpret,
    )(table_flat, lengths, q, pool_k, pool_v)


def paged_attention(q, pool_k, pool_v, block_table, lengths):
    """Dispatcher: the Pallas kernel on TPU when the shapes meet its
    assumptions (single-token query, block_size a lane multiple,
    head_dim a 64-multiple like the flash gate, heads within the
    stats scratch's 128 lanes), XLA gather otherwise (CPU tests, odd
    shapes).  KFS_DISABLE_PAGED_KERNEL=1 forces the XLA path — the
    on-chip A/B kill-switch, mirroring the flash kernel's
    KFS_DISABLE_FLASH.  NOTE: this branch runs at TRACE time inside
    the jitted decode function, so the env var is read once at the
    first decode compile (effectively process start); flipping it
    later has no effect in-process — restart the replica to switch
    paths (same semantics as KFS_DISABLE_FLASH)."""
    import os

    from kfserving_tpu.ops.attention import _tpu_backend

    bs = pool_k.shape[1]
    d = q.shape[-1]
    h = q.shape[2]
    if (_tpu_backend() and q.shape[1] == 1 and h <= 128
            and bs % 128 == 0 and d % 64 == 0
            and os.environ.get("KFS_DISABLE_PAGED_KERNEL", "")
            in ("", "0", "false")):
        return paged_attention_tpu(q, pool_k, pool_v, block_table,
                                   lengths)
    return paged_attention_xla(q, pool_k, pool_v, block_table, lengths)


def paged_attention_xla(q, pool_k, pool_v, block_table, lengths):
    b, lq, h, d = q.shape
    nb, bs, _, _ = pool_k.shape
    mb = block_table.shape[1]
    # Clamp -1 (unallocated) to 0: masked out below, and XLA's gather
    # clamps anyway — explicit is better than relying on OOB behavior.
    table = jnp.maximum(block_table, 0)
    # [B, MB, BS, H, D] -> [B, MB*BS, H, D]
    k = pool_k[table].reshape(b, mb * bs, h, d)
    v = pool_v[table].reshape(b, mb * bs, h, d)
    positions = jnp.arange(mb * bs)[None, :]
    mask = (positions < lengths[:, None])[:, None, None, :]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights,
                     v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_write(pool_k, pool_v, k_step, v_step, block_table,
                positions):
    """Scatter a step's k/v into the pools at each slot's positions.

    Two call shapes, distinguished at trace time:
      decode:        k/v [B, H, D],    positions [B]
      chunk prefill: k/v [B, L, H, D], positions [B, L]
    Unallocated targets (-1 in the table) AND positions past the
    table's coverage (the engine parks mid-prefill slots on an
    out-of-range feed-position sentinel so speculative decode waves
    cannot corrupt chunks already written) drop via OOB sentinel —
    never clamp: a clamped OOB write would land inside another
    position's block.

    Speculative verify rides the chunked shape: the K+1-position
    dispatch writes k/v for every PROPOSED position [L, L+K], accepted
    or not.  That needs no rollback — rejected positions hold garbage
    the per-query causal mask keeps unreachable (no committed query
    sits past the first rejection), and the next wave over the slot
    re-writes those very positions before its own attention reads
    them.  Only the drop-never-clamp rule above makes the parked-slot
    and near-max_seq overrun cases of that scheme safe."""
    bs = pool_k.shape[1]
    mb = block_table.shape[1]
    chunked = positions.ndim == 2
    block_idx = positions // bs
    offs = positions % bs
    rows = jnp.arange(block_table.shape[0])
    if chunked:
        rows = rows[:, None]
    blocks = block_table[rows, jnp.minimum(block_idx, mb - 1)]
    # -1 (unallocated) or past-the-table positions -> OOB sentinel so
    # mode="drop" discards the write.
    blocks = jnp.where((blocks < 0) | (block_idx >= mb),
                       pool_k.shape[0], blocks)
    pool_k = pool_k.at[blocks, offs].set(
        k_step.astype(pool_k.dtype), mode="drop")
    pool_v = pool_v.at[blocks, offs].set(
        v_step.astype(pool_v.dtype), mode="drop")
    return pool_k, pool_v


def paged_prefill_attention_xla(q, pool_k, pool_v, block_table,
                                q_positions):
    """Chunk-prefill attention: multi-token queries over the paged
    pool with PER-QUERY causal masking (query at absolute position p
    attends keys at positions <= p).  The single-length mask of
    `paged_attention_xla` cannot express this — a chunk's later
    queries see more of the pool than its earlier ones.

    q           [B, L, H, D]   the chunk's queries (L > 1)
    q_positions [B, L] int32   absolute position per query; the
                               engine parks padding queries of a
                               partial final chunk on an out-of-range
                               sentinel (their output is discarded,
                               the mask keeps them finite)
    Returns [B, L, H, D]."""
    b, lq, h, d = q.shape
    nb, bs, _, _ = pool_k.shape
    mb = block_table.shape[1]
    table = jnp.maximum(block_table, 0)
    k = pool_k[table].reshape(b, mb * bs, h, d)
    v = pool_v[table].reshape(b, mb * bs, h, d)
    key_pos = jnp.arange(mb * bs)[None, None, :]          # [1, 1, K]
    mask = (key_pos <= q_positions[:, :, None])[:, None]  # [B,1,L,K]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights,
                     v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_insert(pool_k, pool_v, k_new, v_new, dest_blocks, lengths):
    """Insert a prefill batch's k/v ([B, L, H, D]) into pool blocks.

    dest_blocks [B, ceil(L/BS)] int32: destination block id per
    L-chunk of each row; -1 chunks drop (bucket padding rows, or
    prefix-cache hits whose blocks already hold the data).  Positions
    beyond lengths[i] within a written block are harmless garbage —
    reads mask by length."""
    b, l, h, d = k_new.shape
    bs = pool_k.shape[1]
    chunks = l // bs
    assert chunks * bs == l, "prefill bucket must be block-aligned"
    dest = jnp.where(dest_blocks < 0, pool_k.shape[0], dest_blocks)
    k_c = k_new.reshape(b * chunks, bs, h, d)
    v_c = v_new.reshape(b * chunks, bs, h, d)
    flat_dest = dest.reshape(b * chunks)
    pool_k = pool_k.at[flat_dest].set(k_c.astype(pool_k.dtype),
                                      mode="drop")
    pool_v = pool_v.at[flat_dest].set(v_c.astype(pool_v.dtype),
                                      mode="drop")
    return pool_k, pool_v
