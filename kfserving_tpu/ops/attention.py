"""Multi-head attention dispatch: Pallas flash kernel on TPU, XLA fallback.

One public entry point, `dot_product_attention(q, k, v, mask=None)`, with
shape [batch, len, heads, head_dim] (BLHD — flax linen convention).  On TPU
backends with seq-len and head_dim meeting the kernel's tiling constraints it
runs the fused Pallas kernel (kfserving_tpu/ops/pallas_attention.py);
otherwise it lowers to the standard einsum formulation, which XLA fuses well
on its own for short sequences.

The kernel exists for the long-sequence serving configs (BERT seq-bucketed
batching, BASELINE.json config #3): at seq >= 1024 the materialized
[B, H, L, L] score tensor becomes HBM-bandwidth-bound; the flash formulation
keeps the running softmax in VMEM.
"""

import functools
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger("kfserving_tpu.ops")

# Pallas TPU kernels need the lane dimension (head_dim) to be a multiple of
# 128 and benefit only past a sequence length that depends on lane fill.
# Measured on v5e (fori-chain device timing, B=8 H=12 D=64, 90%-full
# suffix padding): at L=512 XLA is 3.1x FASTER than the kernel (0.13 vs
# 0.42 ms/step — a half-lane head dim wastes the MXU and XLA's fused
# softmax is excellent while the score tensor is small); the kernel wins
# from L~1024 (1.5x) and dominates at long context (57x at L=8192 where
# XLA materializes [B,H,L,L] scores).
_FLASH_MIN_SEQ = 512        # full-lane head dims (D % 128 == 0)
_FLASH_MIN_SEQ_HALF_LANE = 1024  # D % 128 != 0 pads the lane width
# Head dims in multiples of 64 are flash-eligible: D=64 pads the
# 128-lane width but measured 34 TF/s on v5e; smaller head dims waste
# more than half the array and fall back to XLA.
_FLASH_HEAD_DIM_MULTIPLE = 64


def _xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mask: Optional[jax.Array]) -> jax.Array:
    """Reference einsum attention in BLHD layout; XLA fuses scale+bias+softmax
    into the two MXU matmuls for short sequences."""
    depth = q.shape[-1]
    scale = jnp.asarray(1.0 / depth ** 0.5, q.dtype)
    # [B, H, Lq, Lk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    if mask is not None:
        big_neg = jnp.finfo(scores.dtype).min
        scores = jnp.where(mask, scores, big_neg)
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    weights = weights.astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


@functools.lru_cache(maxsize=1)
def _tpu_backend() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _flash_eligible(q: jax.Array) -> bool:
    """Shape/backend gate for the fused kernel.  Mask handling is the
    dispatcher's job: suffix key padding rides the kernel as kv_lengths
    (non-causal only); every other mask pattern serves via XLA.
    KFS_DISABLE_FLASH=1 forces the XLA path (A/B benchmarking)."""
    if os.getenv("KFS_DISABLE_FLASH", "") not in ("", "0", "false"):
        return False
    if not _tpu_backend():
        return False
    _, L, _, D = q.shape
    if D % _FLASH_HEAD_DIM_MULTIPLE != 0:
        return False
    min_seq = (_FLASH_MIN_SEQ if D % 128 == 0
               else _FLASH_MIN_SEQ_HALF_LANE)
    return L >= min_seq


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          mask: Optional[jax.Array] = None,
                          causal: bool = False,
                          kv_lengths: Optional[jax.Array] = None,
                          prefix_padding: bool = False
                          ) -> jax.Array:
    """Attention over [batch, len, heads, head_dim] tensors.

    mask: optional broadcastable boolean [B, H, Lq, Lk] (True = attend).
    causal: apply a causal mask (decoder serving).  Composes with an
        explicit mask (logical AND); the flash kernel path requires the
        causal-only case.
    kv_lengths: optional int32 [B] declaring suffix key padding (real
        keys then padding) — the flash kernel masks it natively, so
        padded seq buckets keep the fused path.  When flash is
        ineligible, the equivalent suffix mask is derived and served via
        XLA.  Mutually exclusive with `mask`: lengths fully determine
        the suffix mask, and an inconsistent explicit mask would be
        silently ignored on the kernel path (callers with arbitrary mask
        patterns pass `mask` alone; the serving path enforces
        suffix-ness host-side in jax_model._check_prefix_mask).
    prefix_padding: declares `mask` to be suffix key padding.  The
        flash path then consumes it as per-row lengths (sum over the
        key axis) while the XLA fallback still applies the mask
        itself — so a contract-violating (non-suffix) mask stays
        correct on XLA and is wrong only where the declaration was
        load-bearing (the kernel), unlike kv_lengths which bakes the
        suffix form into both paths.
    """
    if kv_lengths is not None and mask is not None:
        raise ValueError(
            "mask and kv_lengths are mutually exclusive: kv_lengths "
            "asserts suffix padding and the flash path would silently "
            "ignore a disagreeing mask; pass the mask alone for "
            "arbitrary patterns (optionally with prefix_padding=True)")
    Lq, Lk = q.shape[1], k.shape[1]
    derived_lengths = None
    if prefix_padding and mask is not None and not causal:
        # mask broadcasts over [B, H, Lq, Lk]; any one query row's key
        # mask gives the row's real-key count for a suffix mask.
        flat = jnp.reshape(mask, (mask.shape[0], -1, mask.shape[-1]))
        derived_lengths = flat[:, 0, :].astype(jnp.int32).sum(-1)
    if kv_lengths is not None and mask is None:
        mask = (jnp.arange(Lk)[None, :]
                < kv_lengths[:, None])[:, None, None, :]
    if causal:
        # KV-cache decode has Lq < Lk: query i sits at absolute position
        # (Lk - Lq + i), so the allowed region is a shifted triangle.
        causal_mask = jnp.tril(
            jnp.ones((Lq, Lk), jnp.bool_), k=Lk - Lq)[None, None, :, :]
        mask = causal_mask if mask is None else (mask & causal_mask)
        # The Pallas kernel's causal mask assumes query i sits at absolute
        # position i, which only holds when Lq == Lk; KV-cache decode
        # (Lq < Lk, shifted triangle) must take the XLA path.  Causal +
        # key-padding composition stays on XLA too.
        flash_ok = (mask is causal_mask and Lq == Lk
                    and kv_lengths is None)
        lengths = None
    else:
        # Non-causal flash handles rectangular (Lq != Lk) grids and
        # key-padding lengths natively.
        flash_ok = (mask is None or kv_lengths is not None
                    or derived_lengths is not None)
        lengths = kv_lengths if kv_lengths is not None else derived_lengths
    if flash_ok and _flash_eligible(q):
        try:
            from kfserving_tpu.ops.pallas_attention import flash_attention

            return flash_attention(q, k, v, causal=causal,
                                   kv_lengths=lengths)
        except Exception as exc:  # pragma: no cover - TPU-only path
            logger.warning("pallas flash attention failed (%s); "
                           "falling back to XLA", exc)
    return _xla_attention(q, k, v, mask)
