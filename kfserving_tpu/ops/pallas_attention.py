"""Flash attention as a Pallas TPU kernel.

Canonical online-softmax formulation (Dao et al.) tiled for the TPU memory
hierarchy: the grid walks (batch*heads, q_blocks, k_blocks) with the k axis
innermost and sequential, keeping the running max / normalizer / output
accumulator for one q tile resident in VMEM scratch.  The [L, L] score
matrix never exists in HBM, which is the whole point — at the serving
sequence lengths BASELINE.json config #3 targets the score tensor is what
turns attention HBM-bandwidth-bound.

Layout contract matches kfserving_tpu.ops.attention: [B, L, H, D] in, same
out.  D must be a multiple of 64 (64 pads the 128-lane width but measured
34 TF/s on v5e; attention.py gates eligibility); L needs a power-of-two
block divisor >= 8 — block sizes adapt downward (512/256/.../8) to divide
any such L, so every legal seq bucket keeps the flash path (128-multiples
get full-width blocks; smaller divisors trade MXU efficiency for
coverage).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _fit_block(block: int, length: int) -> Optional[int]:
    """Largest candidate block (<= requested) dividing `length`, or
    None when no power-of-two >= 8 divides it — the caller raises the
    documented error rather than launching the kernel with an unaligned
    block (Mosaic mis-lowers those)."""
    for b in (block, 512, 256, 128, 64, 32, 16, 8):
        if b <= block and length % b == 0:
            return b
    return None


def _flash_kernel(*refs,
                  causal: bool, scale: float, block_q: int, block_k: int,
                  has_lengths: bool):
    if has_lengths:
        # Scalar-prefetch layout: the lengths vector precedes the
        # tensor refs (PrefetchScalarGridSpec).
        len_ref, q_ref, k_ref, v_ref, o_ref, \
            m_scratch, l_scratch, acc_scratch = refs
    else:
        len_ref = None
        q_ref, k_ref, v_ref, o_ref, \
            m_scratch, l_scratch, acc_scratch = refs
    bh_idx = pl.program_id(0)
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    num_k = pl.num_programs(2)
    row_len = len_ref[bh_idx] if has_lengths else None

    @pl.when(k_idx == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    def _run_block():
        # Dots take the inputs' native (bf16) dtype — the MXU multiplies
        # bf16 at full rate with fp32 accumulation; upcasting first
        # halves throughput.  Stats/accumulator stay fp32.
        q = q_ref[0]                                      # [bq, d]
        k = k_ref[0]                                      # [bk, d]
        s = jax.lax.dot_general(                          # [bq, bk] fp32
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal or has_lengths:
            k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if has_lengths:
            # Key-padding: keys at positions >= this batch row's real
            # length never contribute (suffix padding from the serving
            # batcher's seq buckets).
            s = jnp.where(k_pos < row_len, s, _NEG_INF)

        m_prev = m_scratch[:]                             # [bq, 1]
        l_prev = l_scratch[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)        # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                            # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)                   # [bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0]                                      # [bk, d]
        pv = jax.lax.dot_general(                         # p rides bf16
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = m_new
        l_scratch[:] = l_new

    pred = None
    if causal:
        # Skip fully-masked k blocks above the diagonal.
        pred = k_idx * block_k <= q_idx * block_q + (block_q - 1)
    if has_lengths:
        # Skip k blocks entirely beyond this row's length (dynamic
        # predicate — pl.when accepts traced conditions).
        beyond = k_idx * block_k < row_len
        pred = beyond if pred is None else (pred & beyond)
    if pred is None:
        _run_block()
    else:
        pl.when(pred)(_run_block)

    @pl.when(k_idx == num_k - 1)
    def _finalize():
        # max() guards rows with length 0 (batch-dim padding): 0/eps
        # instead of 0/0 NaN; those rows are sliced away by the caller.
        o_ref[0] = (acc_scratch[:]
                    / jnp.maximum(l_scratch[:], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    kv_lengths: "jax.Array | None" = None) -> jax.Array:
    """Fused attention over [B, L, H, D]; returns [B, L, H, D].

    kv_lengths: optional int32 [B] — per-row count of real keys (suffix
    padding beyond is masked inside the kernel, and fully-padded k
    blocks are skipped).  This is what lets the serving path's
    seq-bucket padding ride the flash kernel instead of falling back
    to XLA with a materialized mask.
    """
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    # Blocks shrink to the largest power-of-two divisor <= the requested
    # size, so L=640 runs with 128-blocks instead of losing the kernel.
    block_q = _fit_block(block_q, Lq)
    block_k = _fit_block(block_k, Lk)
    if block_q is None or block_k is None:
        raise ValueError(
            f"seq lens ({Lq}, {Lk}) need a power-of-two block divisor "
            ">= 8; pad sequences to a multiple of 8")
    scale = 1.0 / D ** 0.5

    # Fold heads into the grid's first axis: BHLD views with one (b,h) slab
    # per program keeps BlockSpecs 3-D and index maps trivial.
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)

    grid = (B * H, Lq // block_q, Lk // block_k)
    has_lengths = kv_lengths is not None
    kernel = functools.partial(
        _flash_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, has_lengths=has_lengths)
    scratch_shapes = [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, D), jnp.float32),
    ]
    out_shape = jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype)
    # jax renamed TPUCompilerParams -> CompilerParams across releases;
    # accept either so the kernel (and its interpret-mode tests) track
    # the installed version instead of one side of the rename.
    _params_cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    params = _params_cls(
        dimension_semantics=("parallel", "parallel", "arbitrary"))

    if has_lengths:
        # Lengths ride as a prefetched scalar vector so the k/v index
        # maps can CLAMP their block index: grid steps beyond a row's
        # last real block re-request the same block, which Mosaic's
        # pipeline elides — short rows in long buckets skip the HBM
        # traffic, not just the FLOPs (the pl.when below only skips
        # compute).
        lengths_bh = jnp.repeat(kv_lengths.astype(jnp.int32), H)

        def kv_index(bh, i, j, lens):
            # index_map signature: (*grid_indices, *scalar_refs)
            last = jnp.maximum(
                (lens[bh] + block_k - 1) // block_k - 1, 0)
            return (bh, jnp.minimum(j, last), 0)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, D),
                             lambda bh, i, j, lens: (bh, i, 0)),
                pl.BlockSpec((1, block_k, D), kv_index),
                pl.BlockSpec((1, block_k, D), kv_index),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, D), lambda bh, i, j, lens: (bh, i, 0)),
            scratch_shapes=scratch_shapes,
        )
        out = pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape,
            compiler_params=params,
        )(lengths_bh, qt, kt, vt)
    else:
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda bh, i, j: (bh, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda bh, i, j: (bh, j, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, D),
                                   lambda bh, i, j: (bh, i, 0)),
            out_shape=out_shape,
            scratch_shapes=scratch_shapes,
            compiler_params=params,
        )(qt, kt, vt)
    return out.reshape(B, H, Lq, D).transpose(0, 2, 1, 3)
