"""TPU hot-op library.

The compute path of the serving runtime: attention (with a Pallas
flash-attention kernel on TPU and a pure-XLA fallback elsewhere), and
quantized/fused primitives used by the model zoo.  The reference delegates
all accelerator execution to third-party servers (SURVEY.md §2.2) so none of
this has a counterpart — it is the TPU-native heart.
"""

from kfserving_tpu.ops.attention import dot_product_attention  # noqa: F401
