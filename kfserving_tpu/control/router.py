"""Ingress router: the Istio-VirtualService + activator equivalent.

Reference routing rules (pkg/controller/v1beta1/inferenceservice/
reconcilers/ingress/ingress_reconciler.go:164-236): top-level traffic goes
to the transformer when one exists, else the predictor; `:explain` paths
go to the explainer; canary splits ride weighted revision targets.  The
activator role (buffer + scale-from-zero, reference
test/benchmark/README.md:14-17) lives here too: a request for a
zero-replica component triggers scale-up and waits for readiness.

One router fronts many InferenceServices; services are addressed by model
name (the isvc name), matching the reference's host-regex authority match
reduced to its observable effect.
"""

import asyncio
import bisect
import hashlib
import itertools
import json
import logging
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import aiohttp

from kfserving_tpu.observability import REGISTRY
from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.accesslog import log_access
from kfserving_tpu.observability.federation import merge_scrapes
from kfserving_tpu.reliability import (
    CircuitBreaker,
    Deadline,
    FaultInjected,
    PRIORITY_HEADER,
    TIMEOUT_HEADER,
    fault_sites,
    faults,
    priority_tier,
)
from kfserving_tpu.server.http import HTTPServer, Request, Response, Router
from kfserving_tpu.tracing import (
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    TraceContext,
    ensure_trace_context,
    tracer,
)

logger = logging.getLogger("kfserving_tpu.control.router")

ACTIVATOR_TIMEOUT_S = 60.0

# Every proxied response is tagged with the revision that served it:
# clients (and tests) can attribute an answer to canary vs stable
# without scraping metrics.
REVISION_HEADER = "x-kfs-revision"


class IngressRouter:
    # Virtual nodes per replica on the consistent-hash ring.  Arc
    # balance decides whether a replica's model share fits its HBM
    # budget, so small fleets need MANY vnodes: at 2 replicas, 32
    # vnodes can split a 20-model catalog 13/7 (the heavy replica
    # thrashes its arc), while 128 keeps splits near-even.  Ring
    # build is O(replicas * vnodes * log) and cached per replica set.
    AFFINITY_VNODES = 128

    def __init__(self, controller, http_port: int = 0, seed: int = 0,
                 upstream_timeout_s: Optional[float] = None,
                 buffer_deadline_s: Optional[float] = None,
                 breaker_factory: Optional[
                     Callable[[str], CircuitBreaker]] = None,
                 swap_hold_max: int = 1024,
                 brownout=None,
                 affinity: Optional[str] = None,
                 affinity_spill: Optional[int] = None):
        self.controller = controller  # Controller (store + reconciler)
        self.http_port = http_port
        self.upstream_timeout_s = upstream_timeout_s or ACTIVATOR_TIMEOUT_S
        # Bounded activator buffering: a request that finds no ready
        # replica (scale-from-zero, recycle swap window) waits at most
        # this long before shedding 503 + Retry-After.  Unbounded
        # parking hides a swap brownout inside "100% success" at
        # 20s+ p99 (VERDICT r3 weak #1); shedding past a deadline is
        # the trade the overload bench proved.
        self.buffer_deadline_s = (buffer_deadline_s
                                  if buffer_deadline_s is not None
                                  else ACTIVATOR_TIMEOUT_S)
        # Announced-swap holds (ISSUE 10): when the orchestrator
        # publishes a drain->activate window for a component, requests
        # that find no replica are HELD in a bounded queue (at most
        # swap_hold_max concurrently; the hold is also bounded by
        # buffer_deadline_s and the request's own budget) instead of
        # shedding 503s across a planned swap.
        self.swap_hold_max = swap_hold_max
        self._swap_held: Dict[str, int] = {}
        # Brownout admission control (ISSUE 12): a BrownoutController
        # whose per-model levels the predictive control loop sets.
        # None = every request admitted (the pre-brownout behavior).
        self.brownout = brownout
        # Model-affinity routing (ISSUE 15): "model" hashes the
        # requested model name onto a consistent ring over the
        # component's replicas, so a fleet fronting a multi-model
        # repository PARTITIONS the model set — each replica's HBM
        # working set shrinks to its ring arc instead of every replica
        # thrashing the whole catalog.  "prefix" (ISSUE 20) hashes the
        # normalized prompt's first-N-block chain digest instead, so
        # conversations sharing a prompt prefix land on the replica
        # whose engine-side prefix index already holds those KV blocks
        # (the digest construction mirrors the engine's, so equal keys
        # really mean shareable blocks).  Both modes ride the SAME
        # ring/vnode/spill machinery, and the breaker/health machinery
        # stays the escape hatch: an unhealthy or overloaded primary
        # spills to the next ring position, and a ring that yields
        # nothing (or an injected `router.affinity_pick` fault) falls
        # back to plain round-robin.  Default "none" keeps the blind
        # round-robin spray (single-model services gain nothing from
        # pinning every request to one replica).
        self.affinity = (affinity if affinity is not None
                         else os.environ.get("KFS_ROUTER_AFFINITY",
                                             "none"))
        # Per-host in-flight ceiling before an affinity pick spills to
        # the next ring position (0 disables spilling-on-load).
        self.affinity_spill = (
            affinity_spill if affinity_spill is not None
            else int(os.environ.get("KFS_ROUTER_AFFINITY_SPILL", "8")))
        # Prefix-affinity key shape: how many leading prompt blocks of
        # how many tokens feed the chain digest.  The block size should
        # match the serving engine's `block_size` so the router's key
        # equals the engine's prefix-index chain for those blocks;
        # the block COUNT bounds both hashing cost and key cardinality
        # (deeper chains over-shard conversations that share a long
        # system prompt but diverge late).
        self.affinity_prefix_blocks = int(os.environ.get(
            "KFS_ROUTER_AFFINITY_PREFIX_BLOCKS", "4"))
        self.affinity_prefix_block_tokens = int(os.environ.get(
            "KFS_ROUTER_AFFINITY_PREFIX_BLOCK", "128"))
        self._host_inflight: Dict[str, int] = {}
        self._ring_cache: Dict[tuple, List[Tuple[int, str]]] = {}
        self._rng = random.Random(seed)
        self._rr = {}  # component_id -> round-robin counter
        self.router = Router()
        self._register_routes()
        self.http_server = HTTPServer(self.router)
        self._session = None
        self.inflight: Dict[str, int] = {}  # component_id -> gauge
        self.request_count: Dict[str, int] = {}
        # OFFERED load per entry component, counted BEFORE the
        # brownout gate: the predictive scaler's arrival signal must
        # see shed demand, or shedding would erase the very signal
        # that justified it (request_count stays "dispatched", the
        # pre-ISSUE-12 meaning).
        self.offered_count: Dict[str, int] = {}
        # One circuit breaker per replica host (KFS_ROUTER_BREAKER_*
        # knobs).  half_open_max=0: recovery is NEVER a trial request —
        # an opened breaker's host rejoins rotation only after the
        # background health reprobe sees it answer its liveness route.
        # Timeouts feed the breaker but (unlike connect failures) do
        # not evict: a hung replica may still be chewing real work, so
        # it is *skipped* and reprobed — graceful degradation instead
        # of an error storm against a sick upstream.
        self._breaker_factory = breaker_factory or (
            lambda host: CircuitBreaker.from_env(
                "KFS_ROUTER", half_open_max=0,
                name=f"replica:{host}"))
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._reprobes: Dict[str, asyncio.Task] = {}

    # -- routes ------------------------------------------------------------
    def _register_routes(self):
        r = self.router
        r.add("POST", "/v1/models/{name}:predict", self._predict)
        r.add("POST", "/v1/models/{name}:explain", self._explain)
        r.add("POST", "/v2/models/{name}/infer", self._predict)
        r.add("POST", "/v2/models/{name}/explain", self._explain)
        # Generative verbs: route to the predictor component like
        # :predict (generation IS prediction in the component model).
        # Token streams pass through WITHOUT body buffering — each
        # upstream SSE chunk is flushed to the client as it arrives —
        # so streams get the same canary split, dead-replica failover
        # (at stream start), and scale-from-zero buffering as every
        # other verb (VERDICT r4: the flagship feature must not route
        # around the deployment machinery).
        r.add("POST", "/v1/models/{name}:generate", self._generate)
        r.add("POST", "/v2/models/{name}/generate", self._generate)
        r.add("POST", "/v2/models/{name}/generate_stream",
              self._generate)
        r.add("GET", "/v1/models/{name}", self._health)
        # Direct-to-predictor lane for transformer->predictor hops (the
        # reference's cluster-local gateway, constants.go:121-127).
        r.add("POST", "/direct/predictor/v1/models/{name}:predict",
              self._predict_direct)
        r.add("POST", "/direct/predictor/v2/models/{name}/infer",
              self._predict_direct)
        # Fleet telemetry: the router's own series plus every replica
        # scrape federated under a `replica` label, and a federated
        # trace view (?trace_id=&limit=&replica= pull from one replica
        # without dumping every ring buffer).
        r.add("GET", "/metrics", self._metrics)
        r.add("GET", "/debug/traces", self._debug_traces)
        # Monitoring-loop federation (ISSUE 3): fleet SLO health and
        # flight-recorder timelines, replica-scraped like the trace
        # view (?replica= narrows to one host).
        r.add("GET", "/v2/health/slo", self._slo_health)
        r.add("GET", "/debug/flightrecorder",
              self._debug_flightrecorder)
        # Device-time profiling federation (ISSUE 6): every replica's
        # engine event timeline merged into ONE Chrome trace, each
        # replica its own Perfetto process group (?replica= narrows to
        # one host; window_s/format pass through).
        r.add("GET", "/debug/profile", self._debug_profile)
        # Cache & cost attribution federation (ISSUE 13): every
        # replica's /debug/cache snapshot keyed under the `replica`
        # label — the feed prefix-affinity routing (ROADMAP item 3)
        # and the HBM residency manager (item 4) will consume.
        r.add("GET", "/debug/cache", self._debug_cache)
        # Telemetry-history federation (ISSUE 17): every replica's
        # ring-TSDB frames keyed under the `replica` label, resampled
        # onto one absolute epoch grid so a fleet rollup can merge
        # them by timestamp (rates sum, everything else means).
        r.add("GET", "/debug/history", self._debug_history)
        # Incident-engine federation (ISSUE 18): every replica's
        # diagnosed incidents keyed under the `replica` label, plus a
        # fleet rollup that dedups by (root cause, model) — the same
        # regression breaching N replicas is ONE fleet incident — and
        # the router's own admission/brownout state beside it (the
        # evidence only this vantage point holds).
        r.add("GET", "/debug/incidents", self._debug_incidents)
        # Progressive-delivery status (ISSUE 4): active rollouts,
        # recent promotions/rollbacks with pinned evidence, and the
        # quarantine ledger.
        r.add("GET", "/v2/rollouts", self._rollouts)

    async def start_async(self, host: str = "127.0.0.1"):
        # force_close: no keep-alive pooling to upstreams.  A reused
        # half-closed socket would raise ServerDisconnectedError before
        # the replica saw anything — indistinguishable from a true
        # mid-request drop, which must NOT be retried (may duplicate
        # inference).  Closing per request makes "ClientError after
        # connect" reliably mean "the request was dispatched", at the
        # cost of a TCP handshake per proxy hop (local links; the
        # reference's activator pays the same per-request dial).
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.upstream_timeout_s),
            connector=aiohttp.TCPConnector(force_close=True))
        await self.http_server.start(host, self.http_port)
        self.http_port = self.http_server.port
        # Publish the cluster-local gateway address: explainer and
        # transformer replicas built after this point get predictor_host
        # injected (orchestrator._inject_predictor_host; subprocess
        # replicas see it as KFS_CLUSTER_LOCAL_URL).  Overwrite
        # unconditionally — a router restart binds a new ephemeral port
        # and a stale address would point new replicas at a dead socket.
        orch = self.controller.reconciler.orchestrator
        if hasattr(orch, "cluster_local_url"):
            orch.cluster_local_url = f"{host}:{self.http_port}"

    async def stop_async(self):
        for task in self._reprobes.values():
            task.cancel()
        if self._reprobes:
            await asyncio.gather(*self._reprobes.values(),
                                 return_exceptions=True)
        self._reprobes.clear()
        if self._session is not None:
            await self._session.close()
            self._session = None
        await self.http_server.stop()

    # -- per-replica circuit breaking ---------------------------------------
    def _breaker(self, host: str) -> CircuitBreaker:
        breaker = self._breakers.get(host)
        if breaker is None:
            breaker = self._breaker_factory(host)
            self._breakers[host] = breaker
        return breaker

    def _record_failure(self, host: str) -> None:
        breaker = self._breaker(host)
        breaker.record_failure()
        if breaker.state != "closed":
            self._ensure_reprobe(host)

    def _host_release(self, host: str) -> None:
        n = self._host_inflight.get(host, 0) - 1
        if n <= 0:
            self._host_inflight.pop(host, None)
        else:
            self._host_inflight[host] = n

    def _record_success(self, host: str) -> None:
        # Success == no failure history worth keeping (record_success
        # clears the rolling window anyway), so drop the entry: the
        # breaker map then holds ONLY hosts with in-window failures,
        # staying bounded under replica churn (a healthy replica that
        # scales away never leaves an entry behind).
        self._breakers.pop(host, None)

    def _ensure_reprobe(self, host: str) -> None:
        task = self._reprobes.get(host)
        if task is not None and not task.done():
            return
        self._reprobes[host] = asyncio.get_running_loop().create_task(
            self._reprobe(host))

    async def _reprobe(self, host: str) -> None:
        """Background recovery path for an open breaker: poll the
        replica's liveness route; the first success closes the breaker
        and rejoins the host to rotation.  Gives up once the replica
        is no longer registered anywhere (evicted / scaled away) —
        its breaker entry is dropped with it."""
        try:
            first = self._breakers.get(host)
            if first is None:
                return
            interval = max(0.05, first.reset_timeout_s / 2.0)
            while self._session is not None:
                await asyncio.sleep(interval)
                # get(), NOT _breaker(): an eviction pops the entry
                # mid-probe, and recreating it would leak a breaker
                # for a dead host:port forever.
                breaker = self._breakers.get(host)
                if breaker is None or breaker.state == "closed":
                    return
                orch = self.controller.reconciler.orchestrator
                known = any(r.host == host
                            for cid in getattr(orch, "state", {})
                            for r in orch.replicas(cid))
                if not known:
                    self._breakers.pop(host, None)
                    return
                if await self._probe_ok(host):
                    logger.info("replica %s answers liveness again; "
                                "closing its breaker", host)
                    breaker.record_success()
                    self._record_success(host)  # absence == closed
                    return
        finally:
            # Self-deregister so replica churn can't grow the task
            # map unboundedly (guard: a newer task may own the slot).
            if self._reprobes.get(host) is asyncio.current_task():
                self._reprobes.pop(host, None)

    async def _probe_ok(self, host: str) -> bool:
        """Strict positive probe for breaker recovery: only a prompt
        2xx-4xx answer counts.  Opposite polarity from
        `_replica_alive` — there a timeout means "busy, don't evict";
        here it means "still not answering, keep the breaker open"."""
        try:
            async with self._session.get(
                    f"http://{host}/",
                    timeout=aiohttp.ClientTimeout(total=2.0)) as resp:
                return resp.status < 500
        except Exception:
            return False

    # -- routing core ------------------------------------------------------
    def _lookup_service(self, name: str):
        """Resolve a request's model name to its InferenceService.  A
        name that is not an isvc may be a TrainedModel under a
        multi-model parent (the reference's TrainedModel URL shape,
        `<isvc-url>/v1/models/<tm>:predict`) — route to the parent's
        predictor fleet; the replica's repository serves the model by
        name.  Returns (isvc, affinity_key): the TRAINED-MODEL name is
        the affinity key, so the parent's fleet partitions the model
        set.  A direct isvc hit gets NO affinity key unless its
        predictor is multi-model: pinning a single-model service's
        whole traffic to one ring home would idle the rest of its
        replicas below the spill ceiling."""
        isvc = self.controller.get(name)
        if isvc is not None:
            multi = bool(getattr(
                getattr(isvc, "predictor", None), "multi_model",
                False))
            return isvc, (name if multi else None)
        tms = getattr(self.controller, "trained_models", None)
        if not tms:
            return None, None
        tm = tms.get(f"default/{name}")
        if tm is None:
            tm = next((t for t in tms.values() if t.name == name),
                      None)
        if tm is None:
            return None, None
        return self.controller.get(tm.inference_service,
                                   tm.namespace), name

    def _prefix_affinity_key(self, body) -> Optional[str]:
        """Chain digest of the request prompt's first N blocks — the
        affinity key for `KFS_ROUTER_AFFINITY=prefix`.  The prompt is
        normalized exactly the way the serving engine will see it
        (byte-tokenizer ids: BOS 256 + utf-8 bytes, int32
        little-endian), then chained with blake2b-16 per block of
        `affinity_prefix_block_tokens` tokens — the identical
        construction the engine's prefix index keys full prompt blocks
        by, so two requests hashing to the same key really do share
        cached KV on the replica the ring pins them to.  A prompt
        shorter than one full block digests whole (short prompts still
        pin consistently); an unparsable body returns None (the caller
        keeps whatever key `_lookup_service` produced)."""
        if not body:
            return None
        try:
            payload = json.loads(body)
        except Exception:
            return None
        inst: Any = payload
        if isinstance(payload, dict):
            insts = payload.get("instances")
            if isinstance(insts, list) and insts:
                inst = insts[0]
        if isinstance(inst, dict):
            inst = inst.get("prompt", inst.get("text_input"))
        if not isinstance(inst, str) or not inst:
            return None
        raw = b"".join(
            t.to_bytes(4, "little")
            for t in [256] + list(inst.encode("utf-8")))
        bs = 4 * max(1, self.affinity_prefix_block_tokens)
        full = min(len(raw) // bs, max(1, self.affinity_prefix_blocks))
        chain = b""
        for c in range(full):
            chain = hashlib.blake2b(chain + raw[c * bs:(c + 1) * bs],
                                    digest_size=16).digest()
        if not full:
            chain = hashlib.blake2b(raw, digest_size=16).digest()
        return chain.hex()

    def _entry_component(self, isvc, verb: str) -> str:
        if verb == "explain":
            if isvc.explainer is not None:
                return "explainer"
            return "predictor"
        if isvc.transformer is not None:
            return "transformer"
        return "predictor"

    def _pick_revision(self, cstatus) -> Optional[str]:
        targets = [t for t in cstatus.traffic if t.percent > 0]
        if not targets:
            return None
        roll = self._rng.uniform(0, 100)
        acc = 0.0
        for t in targets:
            acc += t.percent
            if roll <= acc:
                return t.revision
        return targets[-1].revision

    def _eligible(self, cid: str, revision: str, exclude=()):
        """Replicas that could serve (revision match, not excluded) —
        BEFORE breaker gating.  The single source of eligibility for
        both the picker and _resolve's circuit-open-vs-scale-from-zero
        distinction, so the two can never drift."""
        return [r for r in
                self.controller.reconciler.orchestrator.replicas(cid)
                if r.revision == revision and r.host not in exclude]

    def _ring(self, hosts: Tuple[str, ...]) -> List[Tuple[int, str]]:
        """Consistent-hash ring over a replica set (cached per set:
        replica churn builds a new ring, stable fleets reuse it)."""
        ring = self._ring_cache.get(hosts)
        if ring is None:
            ring = sorted(
                (int(hashlib.md5(f"{host}#{v}".encode())
                     .hexdigest()[:8], 16), host)
                for host in hosts
                for v in range(self.AFFINITY_VNODES))
            if len(self._ring_cache) >= 64:  # bounded under churn
                self._ring_cache.clear()
            self._ring_cache[hosts] = ring
        return ring

    def _affinity_pick(self, affinity_key: str, replicas, gate
                       ) -> Optional[str]:
        """Walk the ring clockwise from the model's hash point: the
        first position is the model's home replica; overload (host
        in-flight at the spill ceiling) or a breaker veto spills to
        the next DISTINCT host.  None = every host vetoed (caller
        falls back to round-robin)."""
        hosts = tuple(sorted(r.host for r in replicas))
        ring = self._ring(hosts)
        point = int(hashlib.md5(affinity_key.encode())
                    .hexdigest()[:8], 16)
        idx = bisect.bisect_left(ring, (point, ""))
        seen = set()
        for i in range(len(ring)):
            host = ring[(idx + i) % len(ring)][1]
            if host in seen:
                continue
            primary = not seen
            seen.add(host)
            if 0 < self.affinity_spill <= \
                    self._host_inflight.get(host, 0):
                continue
            breaker = gate(host)
            if breaker is not None and not breaker.allow():
                continue
            obs.router_affinity_total().labels(
                mode=self.affinity,
                outcome="ring" if primary else "spill").inc()
            return host
        return None

    def _pick_replica(self, cid: str, revision: str,
                      exclude=(), affinity_key: Optional[str] = None
                      ) -> Optional[str]:
        # A host whose breaker is open is skipped exactly like an
        # excluded one: traffic flows to the healthy replicas while
        # the background reprobe decides when the sick one returns.
        # Filtering reads `state` (pure); allow() — which consumes a
        # half-open trial slot — runs only on the replica round-robin
        # actually picks, so candidates that lose the pick never burn
        # their trial (matters for caller-supplied breaker factories
        # with half_open_max > 0).
        # .get(), never _breaker(): a host with no failure history has
        # no entry (== closed), and creating one per filtered host
        # would grow the map with every replica ever seen.
        def gate(host):
            return self._breakers.get(host)

        replicas = []
        for r in self._eligible(cid, revision, exclude):
            breaker = gate(r.host)
            if breaker is not None and breaker.state == "open":
                obs.router_rotation_skips_total().labels(
                    replica=r.host).inc()
                continue
            replicas.append(r)
        if not replicas:
            return None
        if affinity_key is not None and len(replicas) > 1:
            host = self._affinity_pick(affinity_key, replicas, gate)
            if host is not None:
                return host
            # Ring exhausted (every host overloaded or breaker-vetoed):
            # the round-robin escape hatch below still applies.
            obs.router_affinity_total().labels(
                mode=self.affinity, outcome="fallback").inc()
        for _ in range(len(replicas)):
            idx = self._rr.get(cid, 0)
            self._rr[cid] = idx + 1
            pick = replicas[idx % len(replicas)]
            breaker = gate(pick.host)
            if breaker is None or breaker.allow():
                return pick.host
        return None

    async def _replica_alive(self, host: str) -> bool:
        """Quick liveness probe (the server's `/` route) deciding
        whether a mid-request failure came from a dead process or a
        transient glitch on a live one.  Only a refused/unroutable
        connection means dead; a probe TIMEOUT is indeterminate (a
        tabular replica chewing a multi-second batch on its event loop
        can't answer) and must classify as alive — evicting a busy
        replica would duplicate its in-flight inference and destroy
        healthy capacity, the exact mistakes the timeout branch of
        _proxy refuses to make."""
        try:
            async with self._session.get(
                    f"http://{host}/",
                    timeout=aiohttp.ClientTimeout(total=2.0)) as resp:
                return resp.status < 500
        except (aiohttp.ClientConnectorError, ConnectionRefusedError,
                OSError):
            return False
        except Exception:
            return True

    async def _mark_failed_and_evict(self, name: str, cname: str,
                                     host: str, failed: set,
                                     resolved=None) -> None:
        """Shared failure bookkeeping for the retry loop: exclude the
        host from further attempts and evict its replica.  Resolves
        through _lookup_service (or the caller's already-resolved
        pair): `name` may be a TrainedModel (the affinity path), and
        its crashed PARENT replica must be evicted and
        standby-promoted exactly like a direct isvc request would —
        otherwise the dead host stays the TM's ring home, eating a
        connect error per request until its breaker trips."""
        failed.add(host)
        isvc, _ = (resolved if resolved is not None
                   else self._lookup_service(name))
        if isvc is not None:
            cid = self.controller.reconciler.component_id(isvc, cname)
            await self._evict_replica(cid, host)

    async def _evict_replica(self, cid: str, host: str) -> None:
        """Drop a replica whose transport failed (crashed process) so
        rotation skips it.  Orchestrators with crash supervision
        (`report_crash`) promote the component's armed standby in the
        same tick; otherwise the reconciler/autoscaler recreates
        capacity on its next pass (the reference leans on kubelet
        restart + readiness for this, SURVEY.md §5.3)."""
        orch = self.controller.reconciler.orchestrator
        report = getattr(orch, "report_crash", None)
        for r in orch.replicas(cid):
            if r.host == host:
                try:
                    if report is not None:
                        await report(r)
                    else:
                        await orch.delete_replica(r)
                except Exception:
                    logger.exception("evicting dead replica %s failed",
                                     host)
                # The host is gone; its breaker (and any reprobe
                # chasing it) goes with it.
                self._breakers.pop(host, None)
                logger.warning("evicted dead replica %s of %s", host, cid)
                return

    async def _resolve(self, name: str, verb: str,
                       component: Optional[str] = None,
                       exclude=(), deadline: Optional[Deadline] = None,
                       resolved=None
                       ) -> Tuple[Optional[str], Optional[str],
                                  Optional[str], Optional[str]]:
        """Returns (host, component_name, revision, error).  `resolved`
        carries a (isvc, affinity_key) pair the caller already looked
        up — the dispatch loop resolves once per REQUEST, not once per
        failover attempt (the TrainedModel fallback scans the catalog
        for non-default namespaces)."""
        isvc, affinity_key = (resolved if resolved is not None
                              else self._lookup_service(name))
        if isvc is None:
            return None, None, None, \
                f"inference service {name} not found"
        cname = component or self._entry_component(isvc, verb)
        key = f"{isvc.namespace}/{isvc.name}"
        status = self.controller.reconciler.status.get(key)
        cstatus = status.components.get(cname) if status else None
        if cstatus is None:
            return None, cname, None, \
                f"component {cname} of {name} not reconciled"
        revision = self._pick_revision(cstatus)
        if revision is None:
            return None, cname, None, \
                f"no traffic targets for {name}/{cname}"
        cid = self.controller.reconciler.component_id(isvc, cname)
        if self.affinity not in ("model", "prefix") or verb == "health":
            affinity_key = None
        if affinity_key is not None and faults.configured(
                fault_sites.ROUTER_AFFINITY_PICK):
            try:
                await faults.inject(fault_sites.ROUTER_AFFINITY_PICK,
                                    key=f"{name} {cname}")
            except FaultInjected:
                # Chaos-proven escape hatch: a broken affinity pick
                # degrades to the blind round-robin spray, never to an
                # unroutable request.
                obs.router_affinity_total().labels(
                    mode=self.affinity, outcome="fallback").inc()
                affinity_key = None
        host = self._pick_replica(cid, revision, exclude=exclude,
                                  affinity_key=affinity_key)
        if host is None:
            # Distinguish "nothing registered" (scale-from-zero: spin
            # up and buffer) from "replicas exist but every breaker is
            # open / every host already failed" — activating there
            # would churn scale() and park each request for the full
            # buffer deadline, the exact error-storm amplification the
            # breaker exists to prevent.  Shed fast instead; the
            # reprobe (or the reconciler) restores capacity.
            if self._eligible(cid, revision, exclude):
                return None, cname, revision, (
                    f"no healthy replicas for {name}/{cname} "
                    f"(circuit open)")
            # Announced swap window: the orchestrator said this
            # component is mid drain->activate — hold (bounded queue)
            # rather than churning scale(); the successor it already
            # has in flight will appear.
            verdict, held_host = await self._hold_for_swap(
                cid, revision, exclude, deadline)
            if verdict == "host":
                host = held_host
            elif verdict == "shed":
                return None, cname, revision, (
                    f"no replicas for {name}/{cname} "
                    f"(swap-hold queue full)")
            else:
                host = await self._activate(isvc, cname, cid, revision,
                                            deadline=deadline)
            if host is None:
                return None, cname, revision, \
                    f"no replicas for {name}/{cname}"
        return host, cname, revision, None

    async def _hold_for_swap(self, cid: str, revision: str, exclude,
                             deadline: Optional[Deadline]
                             ) -> Tuple[str, Optional[str]]:
        """Hold a request across an announced swap window.  Returns
        ("host", h) when a replica (re)appeared inside the hold
        budget, ("shed", None) when the bounded queue is full, and
        ("pass", None) when no window is announced (or it closed
        without a replica — the activator path takes over)."""
        orch = self.controller.reconciler.orchestrator
        announced = getattr(orch, "swap_announced", None)
        if not announced or cid not in announced:
            return "pass", None
        loop = asyncio.get_running_loop()
        if loop.time() >= announced.get(cid, 0.0):
            return "pass", None
        held = self._swap_held.get(cid, 0)
        if held >= self.swap_hold_max:
            obs.router_swap_held_total().labels(outcome="shed").inc()
            return "shed", None
        budget_s = self.buffer_deadline_s
        if deadline is not None:
            budget_s = min(budget_s, max(0.0, deadline.remaining_s()))
        start = loop.time()
        until = start + budget_s
        self._swap_held[cid] = held + 1
        try:
            while loop.time() < until:
                host = self._pick_replica(cid, revision,
                                          exclude=exclude)
                if host is not None:
                    hold_ms = (loop.time() - start) * 1000.0
                    obs.router_swap_held_total().labels(
                        outcome="served").inc()
                    obs.router_swap_hold_ms().observe(hold_ms)
                    return "host", host
                if cid not in announced and \
                        not getattr(orch, "pending_creates",
                                    lambda c, r: 0)(cid, revision):
                    # Window closed with nothing in flight (failed
                    # swap, incumbent kept or reconciler's turn):
                    # stop holding, let the activator decide.
                    return "pass", None
                await asyncio.sleep(0.02)
            obs.router_swap_held_total().labels(
                outcome="expired").inc()
            return "pass", None
        finally:
            n = self._swap_held.get(cid, 1) - 1
            if n <= 0:
                self._swap_held.pop(cid, None)
            else:
                self._swap_held[cid] = n

    async def _activate(self, isvc, cname: str, cid: str,
                        revision: str,
                        deadline: Optional[Deadline] = None
                        ) -> Optional[str]:
        """Scale-from-zero: bring up one replica and wait (activator
        buffering).  The spawn runs as a BACKGROUND task: a cold load
        (artifact download + compile) can dwarf any request budget,
        and the buffering request must honor its deadline — bounded
        wait then 504 — never ride the spawn to completion.  The
        spawn itself keeps running past the shed, so the capacity
        still arrives for the client's retry."""
        logger.info("activating %s (scale from zero)", cid)
        scale_task = asyncio.get_running_loop().create_task(
            self.controller.reconciler.scale(isvc, cname, 1))
        # Activator buffering is bounded by BOTH the router's own
        # deadline and the request's remaining budget: parking a
        # 2s-budget request for a 60s scale-up serves nobody.
        budget_s = self.buffer_deadline_s
        if deadline is not None:
            budget_s = min(budget_s, max(0.0, deadline.remaining_s()))
        until = asyncio.get_running_loop().time() + budget_s
        try:
            while asyncio.get_running_loop().time() < until:
                host = self._pick_replica(cid, revision)
                if host is not None:
                    return host
                if scale_task is not None and scale_task.done() and \
                        scale_task.exception() is not None:
                    # A racing create (e.g. a recycle swap) may win
                    # the chip and fail this one — the poll still
                    # succeeds off the winner's replica.  But if
                    # nothing else is creating one, the failure is
                    # deterministic (bad spec, storage error): fail
                    # fast instead of hanging the client for the
                    # full poll.
                    logger.error("activation scale for %s failed",
                                 cid, exc_info=scale_task.exception())
                    pending = getattr(
                        self.controller.reconciler.orchestrator,
                        "pending_creates", lambda c, r: 0)
                    if pending(cid, revision) == 0 and \
                            self._pick_replica(cid, revision) is None:
                        return None
                    scale_task = None  # handled; keep polling
                await asyncio.sleep(0.05)
            return None
        finally:
            # EVERY exit (served off a racing create, budget shed,
            # fail-fast) leaves the spawn finishing in the
            # background for the next request; the callback keeps a
            # late failure from dying as an unretrieved task
            # exception.
            if scale_task is not None and not scale_task.done():
                scale_task.add_done_callback(
                    self._log_late_activation)

    @staticmethod
    def _log_late_activation(task: asyncio.Task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            logger.warning("background activation scale failed: %s",
                           exc)

    # -- handlers ----------------------------------------------------------
    async def _predict(self, req: Request) -> Response:
        return await self._proxy(req, "predict")

    async def _explain(self, req: Request) -> Response:
        return await self._proxy(req, "explain")

    async def _generate(self, req: Request) -> Response:
        # stream_ok: the upstream may answer with an SSE body (the
        # dedicated /generate_stream route or the {"stream": true}
        # upgrade) — pass it through chunk-by-chunk, and drop the
        # total-duration timeout in favor of an inter-chunk one (a
        # legitimate generation can outlive any fixed total budget;
        # a hung replica stops producing chunks and still trips).
        return await self._proxy(req, "predict", component="predictor",
                                 stream_ok=True)

    async def _predict_direct(self, req: Request) -> Response:
        return await self._proxy(req, "predict", component="predictor",
                                 strip_prefix="/direct/predictor")

    async def _health(self, req: Request) -> Response:
        return await self._proxy(req, "health")

    # -- fleet telemetry ---------------------------------------------------
    def _replica_hosts(self):
        """Every replica host currently registered anywhere (the
        federation scrape set)."""
        orch = self.controller.reconciler.orchestrator
        hosts = []
        for cid in getattr(orch, "state", {}):
            for r in orch.replicas(cid):
                if r.host not in hosts:
                    hosts.append(r.host)
        return hosts

    def _refresh_own_series(self) -> None:
        """Mirror the router's live dict-based telemetry (kept as
        plain dicts — the autoscaler reads them directly) into the
        registry at scrape time."""
        for cid, v in self.inflight.items():
            obs.router_inflight().labels(component=cid).set(v)
        for cid, v in self.request_count.items():
            # Mirror, not increment: the dict is the source of truth
            # and the registry child just exposes its current total.
            obs.router_requests_total().labels(
                component=cid).value = float(v)

    async def _scrape(self, host: str, path: str,
                      accept: Optional[str] = None) -> Optional[str]:
        """One replica GET with a bounded timeout; None on any
        failure (a sick replica must not fail the fleet scrape)."""
        headers = {"accept": accept} if accept else None
        try:
            async with self._session.get(
                    f"http://{host}{path}", headers=headers,
                    timeout=aiohttp.ClientTimeout(total=2.0)) as resp:
                if resp.status != 200:
                    return None
                return await resp.text()
        except Exception:
            logger.debug("scrape of %s%s failed", host, path)
            return None

    async def _scrape_json_all(self, hosts, path: str):
        """Concurrent JSON scrape of `path` from every host: the
        shared fan-out of all federated debug/health views.  Yields
        (host, parsed body) pairs; unreachable hosts and non-JSON
        answers are skipped (a sick replica must not fail the fleet
        view), and N sick replicas cost ONE scrape timeout, not N."""
        if self._session is None or not hosts:
            return []
        texts = await asyncio.gather(
            *[self._scrape(host, path) for host in hosts])
        out = []
        for host, text in zip(hosts, texts):
            if text is None:
                continue
            try:
                out.append((host, json.loads(text)))
            except ValueError:
                continue
        return out

    async def _metrics(self, req: Request) -> Response:
        self._refresh_own_series()
        want_om = "application/openmetrics-text" in \
            req.headers.get("accept", "")
        lines = REGISTRY.render_lines(exemplars=want_om)
        if req.query.get("federate", "1") != "0" \
                and self._session is not None:
            hosts = self._replica_hosts()
            # Concurrent scrapes: N sick replicas must cost ONE
            # 2s timeout, not N sequential ones (a hung fleet is
            # exactly when the scrape must still answer fast).
            texts = await asyncio.gather(
                *[self._scrape(host, "/metrics",
                               accept="application/openmetrics-text")
                  for host in hosts])
            # Family-grouped merge: each metric declared once, all of
            # its samples (own + per-replica) contiguous — strict
            # parsers reject re-declared or scattered families.
            lines = merge_scrapes(
                lines,
                [(host, text) for host, text in zip(hosts, texts)
                 if text is not None],
                keep_exemplars=want_om)
        body = "\n".join(lines) + "\n"
        if want_om:
            body += "# EOF\n"
            ctype = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8")
        else:
            ctype = "text/plain; version=0.0.4"
        return Response(body.encode(),
                        headers={"content-type": ctype})

    async def _debug_traces(self, req: Request) -> Response:
        trace_id = req.query.get("trace_id")
        try:
            limit = int(req.query.get("limit", "100"))
        except ValueError:
            return Response(b'{"error": "limit must be an integer"}',
                            status=400)
        only = req.query.get("replica")
        # Dedup key: in-process deployments share ONE tracer between
        # router and replicas, so the router's local read and the
        # federation scrape return the same spans — merge them by
        # identity (replica-labeled copy wins, except router-minted
        # spans keep their router attribution).
        merged: Dict[tuple, dict] = {}

        def add(span: dict, source: str):
            key = (span.get("trace_id"), span.get("name"),
                   span.get("start"), span.get("duration_ms"))
            if key in merged and span.get("name", "").startswith(
                    "router."):
                return
            merged[key] = dict(span, replica=source)

        if only is None or only == "router":
            for s in tracer.spans(trace_id, limit):
                add(s, "router")
        qs = f"?limit={limit}"
        if trace_id:
            qs += f"&trace_id={trace_id}"
        if only == "router":
            hosts = []
        elif only is not None:
            hosts = [only]
        else:
            hosts = self._replica_hosts()
        for host, body in await self._scrape_json_all(
                hosts, f"/debug/traces{qs}"):
            for s in body.get("spans", []):
                add(s, host)
        return Response(json.dumps(
            {"spans": list(merged.values())}).encode())

    async def _slo_health(self, req: Request) -> Response:
        """Fleet SLO view: every replica's /v2/health/slo merged under
        its host, plus the union of alerting (replica, model) pairs —
        one scrape answers "is anything burning budget anywhere"."""
        qs = "?refresh=1" if req.query.get("refresh") == "1" else ""
        replicas: Dict[str, dict] = {}
        alerting = []
        for host, body in await self._scrape_json_all(
                self._replica_hosts(), f"/v2/health/slo{qs}"):
            replicas[host] = body
            for model in body.get("alerting", []):
                alerting.append({"replica": host, "model": model})
        return Response(json.dumps({
            "healthy": not alerting,
            "alerting": alerting,
            "replicas": replicas,
        }).encode())

    async def _rollouts(self, req: Request) -> Response:
        """Progressive-delivery status: the rollout manager's active
        and recent records (with pinned rollback evidence) plus the
        reconciler's quarantine ledger.  Answers even when no manager
        is wired (quarantine still reported) — observability must not
        depend on the optional control loop."""
        manager = getattr(self.controller, "rollout_manager", None)
        if manager is not None:
            body = manager.report()
        else:
            body = {"active": [], "history": [],
                    "quarantine":
                        self.controller.reconciler.quarantine_report()}
        return Response(json.dumps(body).encode())

    async def _debug_profile(self, req: Request) -> Response:
        """Fleet device-time profile: per-replica engine timelines as
        one merged Chrome trace (each replica re-pid'd into its own
        Perfetto process group), or per-replica raw event lists under
        ?format=events."""
        from kfserving_tpu.observability.profiling import merge_traces

        window = req.query.get("window_s")
        try:
            float(window) if window else None
        except ValueError:
            return Response(
                b'{"error": "window_s must be a number"}', status=400)
        fmt = req.query.get("format", "trace_json")
        if fmt not in ("trace_json", "events"):
            return Response(
                b'{"error": "format must be trace_json or events"}',
                status=400)
        only = req.query.get("replica")
        hosts = [only] if only else self._replica_hosts()
        qs = f"?format={fmt}"
        if window:
            qs += f"&window_s={window}"
        scraped = await self._scrape_json_all(hosts,
                                              f"/debug/profile{qs}")
        if fmt == "events":
            return Response(json.dumps({
                "replicas": {host: body for host, body in scraped},
            }).encode())
        return Response(json.dumps(merge_traces(
            [(host, body) for host, body in scraped])).encode())

    async def _debug_cache(self, req: Request) -> Response:
        """Federated cache view: each replica's /debug/cache body
        under its `replica` host key, plus a fleet rollup (index
        entries, hit totals) so one scrape answers "where are the warm
        prefixes".  ?replica= narrows to one host; ?top_k= passes
        through to the replicas' hot-chain census."""
        only = req.query.get("replica")
        top_k = req.query.get("top_k")
        top_cost = req.query.get("top_cost")
        for raw in (top_k, top_cost):
            if raw is not None:
                try:
                    int(raw)
                except ValueError:
                    return Response(
                        b'{"error": "top_k and top_cost must be '
                        b'integers"}', status=400)
        hosts = [only] if only else self._replica_hosts()
        params = []
        if top_k:
            params.append(f"top_k={top_k}")
        if top_cost:
            params.append(f"top_cost={top_cost}")
        qs = ("?" + "&".join(params)) if params else ""
        replicas: Dict[str, dict] = {}
        totals = {"index_entries": 0, "prefix_hits": 0,
                  "prefix_misses": 0, "prefill_tokens_saved": 0,
                  "host_tier_blocks": 0, "host_tier_spills": 0,
                  "host_tier_faulted_blocks": 0,
                  "host_tier_tokens_saved": 0}
        for host, body in await self._scrape_json_all(
                hosts, f"/debug/cache{qs}"):
            replicas[host] = body
            for snap in (body.get("models") or {}).values():
                if not snap.get("paged"):
                    continue
                totals["index_entries"] += snap.get("index_entries", 0)
                pool = snap.get("pool") or {}
                totals["prefix_hits"] += pool.get("prefix_hits", 0)
                totals["prefix_misses"] += pool.get(
                    "prefix_misses", 0)
                totals["prefill_tokens_saved"] += pool.get(
                    "prefill_tokens_saved", 0)
                totals["host_tier_tokens_saved"] += pool.get(
                    "host_tier_tokens_saved", 0)
            # Host KV tiers (ISSUE 16): where evicted conversation
            # state is parked, fleet-wide.
            for tier in (body.get("host_tier") or {}).values():
                totals["host_tier_blocks"] += tier.get(
                    "used_blocks", 0)
                totals["host_tier_spills"] += tier.get("spills", 0)
                totals["host_tier_faulted_blocks"] += tier.get(
                    "faulted_blocks", 0)
        return Response(json.dumps({
            "replicas": replicas,
            "fleet": totals,
        }).encode())

    async def _debug_history(self, req: Request) -> Response:
        """Federated telemetry history: each replica's /debug/history
        frames under its `replica` host key, plus a fleet rollup per
        (series, labels) merged by grid timestamp — rates (counter
        deltas/s) SUM across replicas, every other kind (gauges,
        quantiles, ratios) means.  The scrape pins `step_s` (default
        1 s) so every replica resamples onto the same absolute epoch
        grid; ?series=/?labels=/?window_s= pass through, ?replica=
        narrows to one host."""
        from urllib.parse import quote

        only = req.query.get("replica")
        step = req.query.get("step_s") or "1"
        window = req.query.get("window_s")
        try:
            float(step)
            if window is not None:
                float(window)
        except ValueError:
            return Response(
                b'{"error": "window_s and step_s must be numbers"}',
                status=400)
        qs = f"?step_s={quote(step)}"
        for param in ("series", "labels"):
            value = req.query.get(param)
            if value:
                qs += f"&{param}={quote(value)}"
        if window:
            qs += f"&window_s={quote(window)}"
        hosts = [only] if only else self._replica_hosts()
        replicas: Dict[str, dict] = {}
        merged: Dict[tuple, dict] = {}
        for host, body in await self._scrape_json_all(
                hosts, f"/debug/history{qs}"):
            replicas[host] = body
            for s in body.get("series") or []:
                key = (s.get("name"),
                       tuple(sorted((s.get("labels") or {}).items())))
                agg = merged.setdefault(key, {
                    "name": s.get("name"),
                    "labels": s.get("labels") or {},
                    "kind": s.get("kind"),
                    "step_s": s.get("step_s"),
                    "buckets": {}})
                for frame in s.get("frames") or []:
                    ts, value = frame[0], frame[1]
                    slot = agg["buckets"].setdefault(ts, [0.0, 0])
                    slot[0] += value
                    slot[1] += 1
        fleet = []
        for agg in merged.values():
            # A per-replica rate sums to the fleet rate; a mean of
            # gauges/quantiles/ratios is the only rollup that does
            # not invent load that never existed.
            summing = agg["kind"] == "rate"
            frames = [[ts, (acc if summing else acc / n)]
                      for ts, (acc, n) in
                      sorted(agg["buckets"].items())]
            fleet.append({"name": agg["name"],
                          "labels": agg["labels"],
                          "kind": agg["kind"],
                          "step_s": agg["step_s"],
                          "frames": frames})
        fleet.sort(key=lambda d: (d["name"],
                                  sorted(d["labels"].items())))
        return Response(json.dumps({
            "replicas": replicas,
            "fleet": fleet,
        }).encode())

    async def _debug_flightrecorder(self, req: Request) -> Response:
        """Federated flight-recorder dump: each replica's entries and
        pinned entries, tagged with the serving replica."""
        try:
            limit = int(req.query.get("limit", "100"))
        except ValueError:
            return Response(b'{"error": "limit must be an integer"}',
                            status=400)
        only = req.query.get("replica")
        hosts = [only] if only else self._replica_hosts()
        pinned_only = req.query.get("pinned", "0") == "1"
        qs = f"?limit={limit}"
        if pinned_only:
            qs += "&pinned=1"
        # Pin-stream filters (ISSUE 18) pass through to every replica
        # AND apply to the supervisor's own recorder below.
        pin_type = req.query.get("pin_type") or None
        since_raw = req.query.get("since_ts")
        try:
            since_ts = float(since_raw) if since_raw else None
        except ValueError:
            return Response(
                b'{"error": "since_ts must be a number"}', status=400)
        if pin_type:
            from urllib.parse import quote
            qs += f"&pin_type={quote(pin_type)}"
        if since_ts is not None:
            qs += f"&since_ts={since_ts}"
        entries: list = []
        pinned: list = []
        # The supervisor's own recorder (failover/swap-failure
        # timelines pinned by the orchestrator's crash supervision)
        # federates as replica="supervisor" — the decision trail of a
        # promotion must be visible in the same place as the request
        # evidence, and it survives the dead replica whose ring died
        # with it.
        if only is None or only == "supervisor":
            recorder = getattr(
                self.controller.reconciler.orchestrator,
                "flight_recorder", None)
            if recorder is not None:
                body = recorder.dump(limit=limit,
                                     pinned_only=pinned_only,
                                     pin_type=pin_type,
                                     since_ts=since_ts)
                entries += [dict(e, replica="supervisor")
                            for e in body.get("entries", [])]
                pinned += [dict(e, replica="supervisor")
                           for e in body.get("pinned", [])]
        if only == "supervisor":
            hosts = []
        for host, body in await self._scrape_json_all(
                hosts, f"/debug/flightrecorder{qs}"):
            entries += [dict(e, replica=host)
                        for e in body.get("entries", [])]
            pinned += [dict(e, replica=host)
                       for e in body.get("pinned", [])]
        return Response(json.dumps(
            {"entries": entries, "pinned": pinned}).encode())

    def _router_admission_state(self) -> Dict[str, Any]:
        """The router's own admission evidence for incident views:
        brownout levels, in-flight gauges, breaker states — the
        vantage point no replica bundle can see."""
        state: Dict[str, Any] = {
            "brownout_levels": (self.brownout.report()
                                if self.brownout is not None else {}),
            "inflight": dict(self.inflight),
            "requests": dict(self.request_count),
            "offered": dict(self.offered_count),
        }
        state["breakers"] = {host: breaker.state
                             for host, breaker
                             in self._breakers.items()}
        return state

    async def _debug_incidents(self, req: Request) -> Response:
        """Federated incident view (ISSUE 18).  `?id=` pulls one full
        record from whichever replica owns it (404 when none does);
        the bare list returns every replica's summaries under its
        host key plus a FLEET rollup deduplicated by (root cause,
        model) — the same regression diagnosed on N replicas merges
        into one fleet incident listing the replicas it hit — and the
        router's own admission/brownout state.  ?replica= narrows,
        ?state=/?limit= pass through."""
        from urllib.parse import quote

        only = req.query.get("replica")
        hosts = [only] if only else self._replica_hosts()
        incident_id = req.query.get("id")
        if incident_id:
            qs = f"?id={quote(incident_id)}"
            for host, body in await self._scrape_json_all(
                    hosts, f"/debug/incidents{qs}"):
                if body.get("id"):
                    return Response(json.dumps(
                        dict(body, replica=host)).encode())
            return Response(
                json.dumps({"error":
                            f"unknown incident {incident_id}"}
                           ).encode(), status=404)
        try:
            limit = int(req.query.get("limit", "50"))
        except ValueError:
            return Response(b'{"error": "limit must be an integer"}',
                            status=400)
        state = req.query.get("state")
        qs = f"?limit={limit}"
        if state:
            qs += f"&state={quote(state)}"
        replicas: Dict[str, dict] = {}
        merged: Dict[tuple, dict] = {}
        for host, body in await self._scrape_json_all(
                hosts, f"/debug/incidents{qs}"):
            replicas[host] = body
            for inc in body.get("incidents") or []:
                key = (inc.get("root_cause"), inc.get("model"))
                fleet_inc = merged.setdefault(key, {
                    "root_cause": inc.get("root_cause"),
                    "model": inc.get("model"),
                    "replicas": [],
                    "incident_ids": [],
                    "count": 0,
                    "open": False,
                    "first_opened_ts": inc.get("opened_ts"),
                    "last_updated_ts": inc.get("updated_ts"),
                    "top_hypothesis": inc.get("top_hypothesis"),
                })
                fleet_inc["count"] += 1
                if host not in fleet_inc["replicas"]:
                    fleet_inc["replicas"].append(host)
                fleet_inc["incident_ids"].append(
                    {"replica": host, "id": inc.get("id")})
                if inc.get("state") == "open":
                    fleet_inc["open"] = True
                opened = inc.get("opened_ts")
                if opened is not None and (
                        fleet_inc["first_opened_ts"] is None
                        or opened < fleet_inc["first_opened_ts"]):
                    fleet_inc["first_opened_ts"] = opened
                updated = inc.get("updated_ts")
                if updated is not None and (
                        fleet_inc["last_updated_ts"] is None
                        or updated > fleet_inc["last_updated_ts"]):
                    fleet_inc["last_updated_ts"] = updated
                    fleet_inc["top_hypothesis"] = \
                        inc.get("top_hypothesis")
        fleet = sorted(
            merged.values(),
            key=lambda f: (not f["open"],
                           -(f["last_updated_ts"] or 0.0)))
        return Response(json.dumps({
            "replicas": replicas,
            "fleet": fleet,
            "open": sum(1 for f in fleet if f["open"]),
            "router": self._router_admission_state(),
        }).encode())

    # Transport-level failover attempts per request: a crashed replica is
    # evicted and the request retries the next one (the reference leans
    # on kubelet restart + readiness gates; a single-host fabric must
    # handle the dead-process window itself).
    MAX_UPSTREAM_ATTEMPTS = 3

    @staticmethod
    def _observe_attempt(name: str, revision: Optional[str],
                         status: int, started: float) -> None:
        """Per-revision request accounting, recorded PER ATTEMPT: a
        canary whose dispatches fail is charged those failures even
        when failover lands the request on the stable revision —
        otherwise an error-storming canary whose traffic always fails
        over would show a spotless per-revision series and never trip
        a rollout gate.  `name` is the OWNING isvc, not the requested
        TrainedModel: revisions belong to the service, and rollout
        cleanup prunes these series by isvc name."""
        if revision is None:
            return
        elapsed_ms = (time.perf_counter() - started) * 1000.0
        obs.revision_requests_total().labels(
            model=name, revision=revision, status=str(status)).inc()
        obs.revision_request_ms().labels(
            model=name, revision=revision).observe(elapsed_ms)

    def _stream_through(self, upstream, gauge_cid: str,
                        name: Optional[str] = None,
                        cname: Optional[str] = None,
                        host: Optional[str] = None) -> Response:
        """Chunk-by-chunk SSE pass-through: no body buffering (the
        server's own transport backpressure applies per chunk), the
        in-flight gauge held for the stream's whole life, and a
        mid-stream upstream death (replica crash, recycle past its
        drain budget) surfaces as a terminal SSE event — never a
        silently dead socket.  The router cannot transparently resume
        a broken generation (the decode state died with the replica,
        and re-running it silently would duplicate tokens already
        delivered) — so when the upstream process is DEAD it emits an
        EXPLICIT retriable failover signal (`finish_reason:
        "failover", retriable: true`), evicts the corpse so the
        client's retry lands on the promoted standby, and leaves
        non-fatal glitches on a live replica as the non-retriable
        error they always were."""
        import aiohttp as _aiohttp

        from kfserving_tpu.server.http import StreamingResponse
        from kfserving_tpu.streams import GuardedStream

        async def chunks():
            try:
                async for chunk in upstream.content.iter_any():
                    yield chunk
            except (_aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                logger.warning("stream from upstream interrupted: %s",
                               e)
                dead = (host is not None
                        and not await self._replica_alive(host))
                if dead:
                    # The serving process is gone mid-generation:
                    # evict it (promoting its standby on supervised
                    # orchestrators) and tell the client — explicitly
                    # — that a retry is safe and capacity is coming.
                    obs.router_stream_failover_total().labels(
                        model=name or "").inc()
                    self._record_failure(host)
                    if name is not None and cname is not None:
                        asyncio.ensure_future(
                            self._mark_failed_and_evict(
                                name, cname, host, set()))
                    # The leading blank line terminates any partial
                    # SSE line the upstream death left dangling, so
                    # the event always parses as its own event.  The
                    # predecessor identity is the KV fetch hint
                    # (ISSUE 19): the client's retry forwards it
                    # (x-kfs-kv-peer, which the proxy retry path also
                    # injects itself) so the successor can pull the
                    # dead conversation's spilled KV from a surviving
                    # peer before re-prefilling.
                    event = json.dumps({
                        "error": ("replica failed mid-stream; "
                                  "standby promotion in progress"),
                        "finish_reason": "failover",
                        "retriable": True,
                        "retry_after_ms": 250,
                        "predecessor": host,
                    })
                    yield b"\n\ndata: " + \
                        event.encode("utf-8") + b"\n\n"
                    return
                yield (b'\n\ndata: {"error": "upstream stream '
                       b'interrupted", "finish_reason": "error"}\n\n')

        def on_close():
            self.inflight[gauge_cid] -= 1
            if host is not None:
                self._host_release(host)
            upstream.close()

        # Same response-header policy as the buffered path: trace-id
        # correlation must survive on the flagship streaming verb.
        headers = {
            k: v for k, v in upstream.headers.items()
            if k.lower() in ("content-type",
                             "inference-header-content-length",
                             REQUEST_ID_HEADER)
            or k.lower().startswith("ce-")}
        return StreamingResponse(GuardedStream(chunks(), on_close),
                                 status=upstream.status,
                                 headers=headers)

    async def _brownout_gate(self, name: str, req: Request,
                             deadline: Optional[Deadline]
                             ) -> Optional[Response]:
        """Admission verdict for one request: None = admitted, else
        the shed Response.  The `router.admission` fault site sits
        here so chaos runs can wedge/fail the admission path itself
        (an injected error sheds exactly like a brownout verdict —
        explicit and retriable)."""
        tier = priority_tier(req.headers.get(PRIORITY_HEADER))
        if faults.configured(fault_sites.ROUTER_ADMISSION):
            try:
                await faults.inject(fault_sites.ROUTER_ADMISSION,
                                    key=f"{name} priority:{tier}")
            except FaultInjected:
                obs.brownout_shed_total().labels(
                    model=name, reason="fault").inc()
                return self._brownout_shed(name, "fault")
        if self.brownout is None:
            return None
        remaining = (deadline.remaining_s()
                     if deadline is not None else None)
        admitted, reason = self.brownout.admit(name, tier, remaining)
        if admitted:
            return None
        return self._brownout_shed(name, reason)

    def _brownout_shed(self, name: str, reason: str) -> Response:
        """The explicit retriable shed: clients must be able to tell
        load management from failure, machine-readably — `retriable`
        in the body, `Retry-After` in the headers."""
        level = self.brownout.level(name) if self.brownout else 0
        retry_after = max(1, int(round(
            getattr(self.brownout, "retry_after_s", 1.0) or 1.0)))
        body = json.dumps({
            "error": f"brownout: request shed ({reason})",
            "retriable": True,
            "reason": reason,
            "brownout_level": level,
        }).encode()
        return Response(body=body, status=503,
                        headers={"retry-after": str(retry_after)})

    async def _proxy(self, req: Request, verb: str,
                     component: Optional[str] = None,
                     strip_prefix: str = "",
                     stream_ok: bool = False) -> Response:
        """Telemetry envelope around the proxy core: joins/mints the
        W3C trace context at ingress, records a router span + latency
        histogram (exemplared with the trace id), counts sheds, and
        emits one JSON access-log line per request."""
        name = req.path_params["name"]
        ctx = ensure_trace_context(req.headers, mint="w3c")
        info: Dict[str, Optional[str]] = {}
        start = time.perf_counter()
        with tracer.span("router.proxy", model=name, verb=verb) as sp:
            resp = await self._proxy_inner(req, verb, ctx, info,
                                           component, strip_prefix,
                                           stream_ok)
            sp["status"] = resp.status
            if info.get("upstream"):
                sp["upstream"] = info["upstream"]
        latency_ms = (time.perf_counter() - start) * 1000.0
        obs.router_request_ms().labels(verb=verb).observe(
            latency_ms, trace_id=ctx.trace_id)
        if resp.status in (502, 503, 504):
            obs.router_shed_total().labels(
                status=str(resp.status)).inc()
        log_access("router", trace_id=ctx.trace_id, model=name,
                   verb=verb, status=resp.status,
                   latency_ms=round(latency_ms, 3),
                   upstream=info.get("upstream"))
        # Echo the trace id even on router-local answers (404/503
        # sheds never reach a replica's echo path).
        resp.headers.setdefault(REQUEST_ID_HEADER, ctx.trace_id)
        # Revision attribution: which side of a canary split answered.
        if info.get("revision"):
            resp.headers.setdefault(REVISION_HEADER, info["revision"])
        return resp

    async def _proxy_inner(self, req: Request, verb: str,
                           ctx: TraceContext,
                           info: Dict[str, Optional[str]],
                           component: Optional[str] = None,
                           strip_prefix: str = "",
                           stream_ok: bool = False) -> Response:
        name = req.path_params["name"]
        path = req.path
        if strip_prefix and path.startswith(strip_prefix):
            path = path[len(strip_prefix):]
        headers = {k: v for k, v in req.headers.items()
                   if k.lower() not in ("host", "content-length",
                                        "connection")}
        # Forward the trace context so router, replica, and engine
        # spans all share one trace id: a W3C-shaped id rides
        # `traceparent` (with this hop's span id as the parent); any
        # client-supplied x-request-id passes through untouched, and a
        # router-minted id fills it for legacy correlation.
        forward = ctx.forward_traceparent()
        if forward is not None:
            headers[TRACEPARENT_HEADER] = forward
        headers.setdefault(REQUEST_ID_HEADER, ctx.trace_id)
        # The client's budget governs the router's OWN waiting
        # (activator buffering, failover attempts), and the replica
        # receives the REMAINING budget, not the original — time spent
        # buffered at the router must not be granted twice.
        deadline = Deadline.from_headers(headers)

        # Brownout admission (ISSUE 12): while the predictive loop
        # has a model browned out, the lowest-priority tiers — and
        # any request whose remaining budget provably cannot cover
        # the observed service time — shed HERE with an explicit
        # retriable 503 + Retry-After, before occupying an upstream
        # slot.  Health probes are never shed: readiness gating must
        # keep seeing the truth during an overload.
        # Traffic is BOOKED under the owning isvc, not the requested
        # model name: a TrainedModel request (affinity path) must feed
        # the same router/{isvc}/{component} series the autoscaler and
        # predictive loop read — per-TM keys would leave a busy
        # multi-model fleet looking idle (and scaled to zero).
        resolved = self._lookup_service(name)
        # Prefix-affinity key (ISSUE 20): computed HERE — the one
        # place the request body is in hand — and threaded through
        # `resolved` so the per-attempt _resolve loop never re-parses
        # the payload.  A body with no extractable prompt keeps the
        # model-name key _lookup_service produced (multi-model
        # partitioning remains the backstop).
        if self.affinity == "prefix" and verb != "health":
            pkey = self._prefix_affinity_key(req.body)
            if pkey is not None:
                resolved = (resolved[0], pkey)
        svc = resolved[0]
        svc_name = svc.name if svc is not None else name
        if verb != "health":
            if svc is not None:
                entry = component or self._entry_component(svc, verb)
                offered_key = f"router/{svc_name}/{entry}"
                self.offered_count[offered_key] = \
                    self.offered_count.get(offered_key, 0) + 1
            # The brownout gate is keyed by the OWNING isvc too: the
            # predictive loop sets levels per service (off the
            # router/{svc}/... series above), so a TrainedModel
            # request must be shed under its parent's level.
            shed = await self._brownout_gate(svc_name, req, deadline)
            if shed is not None:
                return shed

        failed: set = set()
        gauge_cid = None
        try:
            for attempt in range(self.MAX_UPSTREAM_ATTEMPTS):
                if deadline is not None and deadline.expired:
                    return Response(
                        body=b'{"error": "request deadline exceeded '
                             b'(router)"}',
                        status=504)
                host, cname, revision, err = await self._resolve(
                    name, verb, component, exclude=failed,
                    deadline=deadline, resolved=resolved)
                info["revision"] = revision
                if err is not None:
                    # Unknown service/component is a true 404; replica
                    # exhaustion (e.g. after evicting a crashed one) is
                    # transient unavailability and must stay 503 so
                    # clients keep retrying.
                    status = (503 if err.startswith(("no replicas",
                                                     "no healthy",
                                                     "no traffic"))
                              else 404)
                    if status == 503 and deadline is not None \
                            and deadline.expired:
                        # The budget died while we buffered/failed
                        # over: every other expiry path answers 504,
                        # and telling the client to retry a request
                        # whose budget is spent helps nobody.
                        return Response(
                            body=b'{"error": "request deadline '
                                 b'exceeded (router buffering)"}',
                            status=504)
                    # json.dumps, not f-string interpolation: err embeds
                    # the client-supplied model name (may contain quotes).
                    resp_headers = {}
                    if status == 503:
                        # Buffer-deadline shed: tell retrying clients
                        # when capacity is likely back (a swap window).
                        resp_headers["retry-after"] = "1"
                    return Response(
                        body=json.dumps({"error": err}).encode(),
                        status=status, headers=resp_headers)
                if gauge_cid is None:
                    # Per-component gauge: the autoscaler must see
                    # transformer and predictor traffic separately.
                    gauge_cid = f"router/{svc_name}/{cname}"
                    self.inflight[gauge_cid] = \
                        self.inflight.get(gauge_cid, 0) + 1
                    self.request_count[gauge_cid] = \
                        self.request_count.get(gauge_cid, 0) + 1
                url = f"http://{host}{path}"
                info["upstream"] = host
                attempt_started = time.perf_counter()
                request_kwargs = {}
                if stream_ok:
                    request_kwargs["timeout"] = aiohttp.ClientTimeout(
                        total=None, sock_connect=10.0,
                        sock_read=self.upstream_timeout_s)
                # Per-host in-flight count: the affinity ring's
                # overload signal (spill past a loaded home replica).
                self._host_inflight[host] = \
                    self._host_inflight.get(host, 0) + 1
                held_host: Optional[str] = host
                try:
                    # Chaos hook: an injected error exercises the same
                    # pre-dispatch failover path a refused connection
                    # would (FaultInjected is handled with
                    # ClientConnectorError below), and an injected
                    # hang sits under the SAME timeout envelope a hung
                    # replica would — wait_for turns hang_s into the
                    # TimeoutError branch (breaker food), not a silent
                    # stall aiohttp's own timeout cannot see.  The
                    # configured() guard keeps the no-faults hot path
                    # at one dict lookup (no Task/timer allocation).
                    # The fault key carries the serving revision
                    # (`revision:<hash>`), so `match=` selectors can
                    # scope chaos to one side of a canary split — the
                    # hardware-free way to drive the rollout manager's
                    # rollback path.
                    if faults.configured(fault_sites.ROUTER_DISPATCH):
                        await asyncio.wait_for(
                            faults.inject(
                                fault_sites.ROUTER_DISPATCH,
                                key=f"{url} revision:{revision}"),
                            timeout=self.upstream_timeout_s)
                    # Forwarded budget computed AFTER the fault sleep:
                    # injected (or real) pre-dispatch latency must
                    # come out of the replica's remaining budget, or
                    # that time is granted twice.
                    if deadline is not None:
                        headers[TIMEOUT_HEADER] = str(max(
                            1, int(deadline.remaining_s() * 1000)))
                    upstream = await self._session.request(
                        req.method, url, data=req.body or None,
                        headers=headers, **request_kwargs)
                    # Any completed HTTP exchange means the transport
                    # to this replica works (the status is the app's
                    # verdict, not the wire's).
                    self._record_success(host)
                    if stream_ok and upstream.headers.get(
                            "content-type", "").startswith(
                                "text/event-stream"):
                        self._observe_attempt(svc_name, revision,
                                              upstream.status,
                                              attempt_started)
                        resp = self._stream_through(upstream,
                                                    gauge_cid,
                                                    name=name,
                                                    cname=cname,
                                                    host=host)
                        # Gauge AND host-inflight slot now owned by
                        # the stream's close hook.
                        gauge_cid = None
                        held_host = None
                        return resp
                    try:
                        body = await upstream.read()
                        # Observed AFTER the body read: a replica that
                        # crashes mid-response raises into the
                        # ClientError branch below, and one physical
                        # attempt must land exactly ONE sample in the
                        # per-revision series the rollout gates on.
                        self._observe_attempt(svc_name, revision,
                                              upstream.status,
                                              attempt_started)
                        resp_headers = {
                            k: v for k, v in upstream.headers.items()
                            if k.lower() in (
                                "content-type",
                                "inference-header-content-length",
                                REQUEST_ID_HEADER)
                            or k.lower().startswith("ce-")}
                        return Response(body=body,
                                        status=upstream.status,
                                        headers=resp_headers)
                    finally:
                        upstream.release()
                except asyncio.TimeoutError:
                    # A slow-but-alive replica (heavy batch, warmup
                    # compile): do NOT evict (it would kill in-flight
                    # work) and do NOT retry (the request may still
                    # execute — a retry would duplicate inference).
                    # The breaker DOES count it: enough consecutive
                    # hangs open it, rotation skips the replica, and
                    # the health reprobe decides when it returns —
                    # degradation to the healthy replicas instead of
                    # feeding every request into a 60s timeout.
                    logger.warning("proxy to %s timed out", url)
                    self._record_failure(host)
                    self._observe_attempt(svc_name, revision, 504,
                                          attempt_started)
                    return Response(
                        body=b'{"error": "upstream timeout"}',
                        status=504)
                except (aiohttp.ClientConnectorError,
                        FaultInjected) as e:
                    # PRE-dispatch connection failure (refused / no
                    # route): the request never reached the replica, so
                    # a retry cannot duplicate inference — evict and
                    # fail over.  HTTP-level errors returned above are
                    # never retried.
                    logger.warning("proxy to %s failed (attempt %d): %s",
                                   url, attempt + 1, e)
                    self._record_failure(host)
                    self._observe_attempt(svc_name, revision, 503,
                                          attempt_started)
                    await self._mark_failed_and_evict(
                        name, cname, host, failed,
                        resolved=resolved)
                    # Failover fetch hint: the retry attempt names the
                    # evicted predecessor so the successor can pull
                    # this session's KV (peer transfer) before it
                    # re-prefills from scratch.  Last eviction wins —
                    # that replica's tier holds the freshest chains.
                    headers["x-kfs-kv-peer"] = f"http://{host}"
                except aiohttp.ClientError as e:
                    # Mid-request/-response failure (reset after
                    # dispatch, truncated read).  Disambiguate with a
                    # liveness probe: a replica that just DIED (crash /
                    # SIGKILL lands here as ECONNRESET when the kill
                    # races an in-flight connect) cannot have returned a
                    # response, so evicting and retrying is safe — the
                    # kubelet-restart role this fabric owns (SURVEY
                    # §5.3).  A replica that still answers its liveness
                    # route had a genuine mid-request glitch: neither
                    # retry (would duplicate inference) nor evict.
                    #
                    # Known window: if the replica executed the request
                    # and crashed while writing the response, the retry
                    # below re-runs the inference — side-effect sinks
                    # (payload-logger mirrors, drift/outlier detector
                    # counters) may observe the request twice.  This is
                    # the availability-over-exactly-once trade the
                    # reference's activator also makes; consumers that
                    # need dedup should key on the logger's request id.
                    logger.warning("proxy to %s failed mid-request: %s",
                                   url, e)
                    self._record_failure(host)
                    self._observe_attempt(svc_name, revision, 502,
                                          attempt_started)
                    if await self._replica_alive(host):
                        return Response(
                            body=b'{"error": "upstream connection '
                                 b'failed"}',
                            status=502)
                    logger.warning(
                        "replica %s dead after mid-request failure: "
                        "evicting and retrying", host)
                    await self._mark_failed_and_evict(
                        name, cname, host, failed,
                        resolved=resolved)
                    # Same fetch hint as the pre-dispatch branch: the
                    # retry carries the dead replica's address for the
                    # successor's peer KV pull.
                    headers["x-kfs-kv-peer"] = f"http://{host}"
                finally:
                    if held_host is not None:
                        self._host_release(held_host)
            return Response(
                body=b'{"error": "upstream unavailable"}', status=503)
        finally:
            # A streaming pass-through transfers gauge ownership to
            # the stream's close hook (the request is still in flight
            # when _proxy returns).
            if gauge_cid is not None:
                self.inflight[gauge_cid] -= 1
