"""Controller: the manager that ties store, reconcilers, and sharding.

Reference: cmd/manager/main.go wires the InferenceService and TrainedModel
reconcilers plus webhooks; here `Controller.apply/delete` run the
defaulting/validation/reconcile pipeline synchronously (no informer lag to
model), and TrainedModel handling drives the HBM shard strategy and the
per-shard models.json files the agent watcher consumes
(reference pkg/controller/v1alpha1/trainedmodel/controller.go:67-147).
"""

import asyncio
import logging
import os
from typing import Dict, List, Optional

from kfserving_tpu.control import modelconfig
from kfserving_tpu.control.reconciler import (
    InferenceServiceReconciler,
    IsvcStatus,
)
from kfserving_tpu.control.sharding import HBMShardStrategy
from kfserving_tpu.control.spec import InferenceService, TrainedModel
from kfserving_tpu.control.validation import (
    ValidationError,
    validate_trained_model,
)

logger = logging.getLogger("kfserving_tpu.control.controller")

DEFAULT_SHARD_BUDGET = 12 * 1024**3  # v5e HBM minus headroom


class Controller:
    def __init__(self, orchestrator, modelconfig_dir: Optional[str] = None,
                 shard_budget_bytes: int = DEFAULT_SHARD_BUDGET):
        self.reconciler = InferenceServiceReconciler(orchestrator)
        self.specs: Dict[str, InferenceService] = {}
        self.trained_models: Dict[str, TrainedModel] = {}
        self.shard_strategies: Dict[str, HBMShardStrategy] = {}
        self.modelconfig_dir = modelconfig_dir
        self.shard_budget_bytes = shard_budget_bytes
        self._shardcfg_lock = asyncio.Lock()

    # -- InferenceService lifecycle ---------------------------------------
    async def apply(self, isvc: InferenceService) -> IsvcStatus:
        """Create-or-update (defaulting + validation + reconcile)."""
        key = f"{isvc.namespace}/{isvc.name}"
        status = await self.reconciler.reconcile(isvc)
        self.specs[key] = isvc
        return status

    async def remove(self, name: str, namespace: str = "default") -> None:
        key = f"{namespace}/{name}"
        isvc = self.specs.pop(key, None)
        if isvc is None:
            return
        # Finalizer deletes child TrainedModels (reference
        # controller.go:208-223).
        for tm_key in [k for k, tm in self.trained_models.items()
                       if tm.inference_service == name
                       and tm.namespace == namespace]:
            await self.remove_trained_model(
                self.trained_models[tm_key].name, namespace)
        await self.reconciler.delete(isvc)

    def get(self, name: str, namespace: str = "default"
            ) -> Optional[InferenceService]:
        return self.specs.get(f"{namespace}/{name}")

    def status_of(self, name: str, namespace: str = "default"
                  ) -> Optional[IsvcStatus]:
        return self.reconciler.status.get(f"{namespace}/{name}")

    # -- TrainedModel lifecycle -------------------------------------------
    async def apply_trained_model(self, tm: TrainedModel) -> dict:
        """Validate, check the parent isvc (exists + multi-model), assign a
        shard, and update that shard's models.json."""
        validate_trained_model(tm)
        parent = self.get(tm.inference_service, tm.namespace)
        if parent is None:
            raise ValidationError(
                f"parent inference service {tm.inference_service} "
                f"not found")
        if not parent.predictor.multi_model:
            raise ValidationError(
                f"inference service {tm.inference_service} is not a "
                f"multi-model predictor")
        strategy = self.shard_strategies.setdefault(
            f"{tm.namespace}/{tm.inference_service}",
            HBMShardStrategy(
                parent.predictor.hbm_budget_bytes
                or self.shard_budget_bytes))
        shard = strategy.get_or_assign(tm)
        self.trained_models[f"{tm.namespace}/{tm.name}"] = tm
        await self._write_shard_config(tm.inference_service,
                                       tm.namespace, strategy, shard)
        # Status URL mirrors the reference (trainedmodel/controller.go:
        # 149-179): <isvc-url>/v1/models/<tm>:predict
        return {"shard": shard,
                "url": f"/v1/models/{tm.name}:predict"}

    async def remove_trained_model(self, name: str,
                                   namespace: str = "default") -> None:
        tm = self.trained_models.pop(f"{namespace}/{name}", None)
        if tm is None:
            return
        strategy = self.shard_strategies.get(
            f"{namespace}/{tm.inference_service}")
        if strategy is None:
            return
        shard = strategy.remove(name)
        if shard is not None:
            await self._write_shard_config(tm.inference_service,
                                           namespace, strategy, shard)

    async def _write_shard_config(self, isvc_name: str, namespace: str,
                                  strategy: HBMShardStrategy,
                                  shard: int) -> None:
        """Write one shard's models.json without stalling the loop
        (kfslint async-blocking: the controller shares the manager's
        event loop with the router, and the modelconfig volume can be
        a slow network mount).  Entries are snapshotted on the loop
        BEFORE the first await — they must reflect the state at call
        time — and writes are serialized so an older snapshot can
        never land after a newer one."""
        if self.modelconfig_dir is None:
            return
        entries: List[dict] = []
        for model_name in strategy.models_on(shard):
            tm = self.trained_models[f"{namespace}/{model_name}"]
            entries.append(tm.to_model_spec())
        path = os.path.join(
            self.modelconfig_dir,
            f"{namespace}-{isvc_name}-shard-{shard}.json")
        async with self._shardcfg_lock:
            await asyncio.get_running_loop().run_in_executor(
                None, modelconfig.write_file, path, entries)
        logger.info("wrote shard config %s (%d models)",
                    path, len(entries))
