"""Actuation backends: turn desired replicas into running servers.

The reference delegates actuation to Knative (the reconciler creates a
Knative Service and Knative makes pods, reference
ksvc_reconciler.go:153-187).  Here actuation is an interface with two
backends:

- InProcessOrchestrator: replicas are real ModelServer instances in this
  process on ephemeral ports — the single-host deployment mode and the
  test backend (the envtest analogue, SURVEY.md §4: real serving, no
  cluster).
- FakeOrchestrator: records desired state for pure reconciler-logic tests
  (golden-object style, reference ingress_reconciler_test.go).

A replica handle is (component_id, revision, host) — the router routes to
hosts and never knows which backend made them.
"""

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("kfserving_tpu.control.orchestrator")


@dataclass
class Replica:
    component_id: str      # "<namespace>/<isvc>/<component>"
    revision: str          # content hash of the component spec
    host: str              # "127.0.0.1:<port>" (in-process backend)
    handle: object = None  # backend-private
    placement: object = None  # SlicePlacement for chip-owning replicas
    # The ComponentSpec the replica was built from — the in-process
    # standby pool arms successors from it (the subprocess backend
    # keeps its copy in _Proc.spec).
    spec: object = None


@dataclass
class _ComponentState:
    replicas: List[Replica] = field(default_factory=list)


class FakeOrchestrator:
    """Records desired replicas; hosts are synthetic."""

    def __init__(self):
        self.state: Dict[str, _ComponentState] = {}
        self._port = 30000

    def replicas(self, component_id: str) -> List[Replica]:
        return list(self.state.get(component_id,
                                   _ComponentState()).replicas)

    async def create_replica(self, component_id: str, revision: str,
                             spec, placement=None) -> Replica:
        self._port += 1
        replica = Replica(component_id, revision,
                          f"fake-host:{self._port}", placement=placement)
        self.state.setdefault(component_id,
                              _ComponentState()).replicas.append(replica)
        return replica

    async def delete_replica(self, replica: Replica) -> None:
        comp = self.state.get(replica.component_id)
        if comp and replica in comp.replicas:
            comp.replicas.remove(replica)


class InProcessOrchestrator:
    """Replicas are ModelServers running in this process.

    model_factory(component_id, spec) -> Model | None builds the served
    model for a replica; the default factory understands the predictor
    frameworks (jax/sklearn/...) and saliency explainers.  Loading runs in
    a thread (compile/IO off the loop).
    """

    def __init__(self, model_factory: Optional[Callable] = None,
                 credentials=None):
        self.model_factory = model_factory or default_model_factory
        # CredentialStore; in-process replicas share this process, so
        # the per-service-account env lands in os.environ at build time
        # (single-host dev mode — subprocess replicas get isolated env).
        self.credentials = credentials
        # Serializes credentialed builds: env mutation + load must not
        # interleave across service accounts (shared os.environ).
        self._cred_lock = asyncio.Lock()
        self.state: Dict[str, _ComponentState] = {}
        # Warm-standby pool (ISSUE 12): fully built replicas kept OUT
        # of the serving state, adopted by scale-ups so a predicted
        # traffic step actuates in one tick instead of a model
        # build+load.  (cid, revision) -> [Replica, ...]; pool depth
        # per component is _standby_targets (default 1 arms nothing —
        # unlike the subprocess backend there is no crash to fail
        # over, so the pool only exists when the predictive loop
        # pre-arms it).
        self._standbys: Dict[tuple, List[Replica]] = {}
        self._standby_targets: Dict[str, int] = {}
        self._standby_arming: Dict[tuple, int] = {}
        self.standby_adoptions = 0
        # Cluster-local gateway address ("host:port"), published by the
        # ingress router at start.  Explainer/transformer replicas get
        # their predictor_host derived from it — the reference injects
        # the predictor's cluster-local URL into those containers
        # (explainer_alibi.go:79-100 --predictor_host).
        self.cluster_local_url: Optional[str] = None

    def replicas(self, component_id: str) -> List[Replica]:
        return list(self.state.get(component_id,
                                   _ComponentState()).replicas)

    # -- warm-standby pool (predictive pre-arming) --------------------------
    def set_standby_target(self, component_id: str, target: int) -> None:
        """Pre-arm `target` standbys for a component's latest serving
        revision (the predictive autoscaler's actuator).  Arming runs
        as background tasks — the control loop's tick never blocks on
        a model load.  A SHRINKING target reaps the excess
        immediately: this backend has no maintenance tick to trim
        the pool later, and an idle armed replica holds a full model
        in memory."""
        target = max(0, min(int(target), 8))
        self._standby_targets[component_id] = target
        for key, pool in list(self._standbys.items()):
            if key[0] != component_id:
                continue
            while len(pool) > target:
                standby = pool.pop()
                asyncio.ensure_future(standby.handle.stop_async())
            if not pool:
                self._standbys.pop(key, None)
        comp = self.state.get(component_id)
        if target == 0 or comp is None or not comp.replicas:
            return
        latest = comp.replicas[-1]
        key = (component_id, latest.revision)
        have = len(self._standbys.get(key, ())) + \
            self._standby_arming.get(key, 0)
        for _ in range(max(0, target - have)):
            self._standby_arming[key] = \
                self._standby_arming.get(key, 0) + 1
            asyncio.ensure_future(self._arm_standby(
                key, latest.spec, latest.placement))

    def standby_target(self, component_id: str) -> int:
        return self._standby_targets.get(component_id, 0)

    def standby_count(self, component_id: str) -> int:
        return sum(len(pool)
                   for (cid, _rev), pool in self._standbys.items()
                   if cid == component_id)

    async def _arm_standby(self, key: tuple, spec, placement) -> None:
        cid, rev = key
        try:
            standby = await self._build_replica(cid, rev, spec,
                                                placement)
        except Exception:
            logger.exception("arming in-process standby for %s failed",
                             cid)
            return
        finally:
            n = self._standby_arming.get(key, 1) - 1
            if n <= 0:
                self._standby_arming.pop(key, None)
            else:
                self._standby_arming[key] = n
        comp = self.state.get(cid)
        if comp is None or not any(r.revision == rev
                                   for r in comp.replicas):
            await standby.handle.stop_async()  # retired while arming
            return
        if len(self._standbys.get(key, ())) >= \
                self._standby_targets.get(cid, 0):
            # Target shrank while this one armed — don't overfill.
            await standby.handle.stop_async()
            return
        self._standbys.setdefault(key, []).append(standby)
        logger.info("in-process standby armed for %s rev=%s at %s",
                    cid, rev[:8], standby.host)

    async def adopt_standby(self, component_id: str,
                            revision: str) -> Optional[Replica]:
        """Scale-up fast path: enter an armed standby into serving.
        None when the pool is dry (caller cold-builds)."""
        pool = self._standbys.get((component_id, revision))
        if not pool:
            return None
        standby = pool.pop(0)
        if not pool:
            self._standbys.pop((component_id, revision), None)
        self.state.setdefault(component_id,
                              _ComponentState()).replicas.append(standby)
        self.standby_adoptions += 1
        from kfserving_tpu.observability import metrics as obs

        obs.lifecycle_promotions_total().labels(
            trigger="scale_up", outcome="promoted").inc()
        logger.info("scale-up adopted in-process standby %s for %s",
                    standby.host, component_id)
        return standby

    async def reap_standbys(self, component_id: str,
                            revision: Optional[str] = None) -> None:
        for key, pool in list(self._standbys.items()):
            cid, rev = key
            if cid != component_id:
                continue
            if revision is not None and rev != revision:
                continue
            self._standbys.pop(key, None)
            for standby in pool:
                await standby.handle.stop_async()

    async def create_replica(self, component_id: str, revision: str,
                             spec, placement=None) -> Replica:
        replica = await self._build_replica(component_id, revision,
                                            spec, placement)
        self.state.setdefault(component_id,
                              _ComponentState()).replicas.append(replica)
        logger.info("replica up: %s rev=%s at %s",
                    component_id, revision[:8], replica.host)
        return replica

    async def _load_or_register(self, model) -> None:
        """Off-loop load (single model) or catalog registration
        (multi-model repository) for a freshly built replica.  Callers
        that inject storage credentials run this inside the credential
        scope — the repository sweep's per-model downloads need them
        exactly like a single model's load does."""
        from kfserving_tpu.model.repository import ModelRepository

        if model is None:
            return
        loop = asyncio.get_running_loop()
        if isinstance(model, ModelRepository):
            register_all = getattr(model, "register_all", None)
            if register_all is not None:
                # Registration of a model set runs off-loop (file I/O
                # per model directory).
                await loop.run_in_executor(None, register_all)
        elif not model.ready:
            await loop.run_in_executor(None, model.load)

    async def _build_replica(self, component_id: str, revision: str,
                             spec, placement=None) -> Replica:
        from kfserving_tpu.model.repository import ModelRepository
        from kfserving_tpu.server.app import ModelServer

        if self.credentials is not None:
            import os

            env = self.credentials.build_env(
                getattr(spec, "service_account_name", "default"))
            # Hold the lock across env-set + load so a concurrent build
            # for another service account can't swap credentials out
            # from under this model's storage download; restore the
            # ambient values afterwards.
            async with self._cred_lock:
                saved = {k: os.environ.get(k) for k in env}
                os.environ.update(env)
                try:
                    model = self.model_factory(component_id, spec)
                    # Registration/load runs INSIDE the credential
                    # scope: a multi-model catalog sweep downloads
                    # per-model artifacts with the same service
                    # account as a single model's load would.
                    await self._load_or_register(model)
                finally:
                    for k, old in saved.items():
                        if old is None:
                            os.environ.pop(k, None)
                        else:
                            os.environ[k] = old
        else:
            model = self.model_factory(component_id, spec)
            await self._load_or_register(model)
        # A factory may return a whole ModelRepository instead of one
        # model: the multi-model replica shape (TrainedModel-style
        # repositories with demand-paged HBM residency) — the server
        # fronts the repository instead of a model list.
        repository = model if isinstance(model, ModelRepository) \
            else None
        if repository is not None:
            model = None
        self._inject_predictor_host(model, spec)
        server = ModelServer(
            http_port=0, enable_docs=False,
            registered_models=repository,
            container_concurrency=getattr(
                spec, "container_concurrency", 0) or 0)
        await server.start_async([model] if model is not None else [],
                                 host="127.0.0.1")
        return Replica(component_id, revision,
                       f"127.0.0.1:{server.http_port}", handle=server,
                       placement=placement, spec=spec)

    def _inject_predictor_host(self, model, spec) -> None:
        """Point an explainer/transformer replica's model at the isvc's
        predictor through the router's direct lane (the reference's
        cluster-local predictor URL, kfmodel.py:24-27)."""
        from kfserving_tpu.control.spec import (
            ExplainerSpec,
            TransformerSpec,
        )

        if model is None or self.cluster_local_url is None:
            return
        if not isinstance(spec, (ExplainerSpec, TransformerSpec)):
            return
        if getattr(model, "predictor_host", None):
            return  # explicitly configured wins
        model.predictor_host = \
            f"{self.cluster_local_url}/direct/predictor"

    async def delete_replica(self, replica: Replica) -> None:
        comp = self.state.get(replica.component_id)
        if comp and replica in comp.replicas:
            comp.replicas.remove(replica)
        server = replica.handle
        if server is not None:
            await server.stop_async()
        logger.info("replica down: %s at %s",
                    replica.component_id, replica.host)

    async def shutdown(self):
        # Armed standbys live outside self.state — stop them first.
        for key, pool in list(self._standbys.items()):
            self._standbys.pop(key, None)
            for standby in pool:
                await standby.handle.stop_async()
        for comp in list(self.state.values()):
            for replica in list(comp.replicas):
                await self.delete_replica(replica)


def default_model_factory(component_id: str, spec):
    """Build the served model for a component spec.

    component kinds map to the reference's container images (SURVEY.md
    §2.1 per-framework predictor specs); model name is the isvc name so
    routes match /v1/models/<isvc>:predict.
    """
    from kfserving_tpu.control.spec import (
        ExplainerSpec,
        PredictorSpec,
        TransformerSpec,
    )

    isvc_name = component_id.split("/")[1]
    if isinstance(spec, PredictorSpec):
        if spec.multi_model:
            if spec.framework != "jax":
                raise ValueError(
                    f"multi-model predictors serve the jax repository "
                    f"shape, not {spec.framework!r}")
            from kfserving_tpu.engine.hbm import HBMManager
            from kfserving_tpu.predictors.jaxserver import (
                JaxModelRepository,
            )

            # storage_uri is the model CATALOG root (one subdir per
            # TrainedModel); every model registers host-side at boot
            # and HBM residency is demand-paged under the spec's
            # per-replica budget — the TrainedModel CRD + agent-puller
            # economics with millisecond activation.
            return JaxModelRepository(
                models_dir=spec.storage_uri,
                hbm=HBMManager(budget_bytes=spec.hbm_budget_bytes))
        if spec.framework == "jax":
            from kfserving_tpu.predictors.jax_model import JaxModel

            # The spec's ParallelismSpec decides the within-replica mesh
            # (placement is a deployment concern; the artifact's
            # config.json stays mesh-agnostic — SURVEY.md §5.8).
            par = getattr(spec, "parallelism", None)
            overrides = {}
            if par is not None and par.chips_per_replica > 1:
                overrides["mesh"] = {
                    "dp": par.dp, "tp": par.tp, "sp": par.sp}
            return JaxModel(isvc_name, spec.storage_uri,
                            config_overrides=overrides)
        if spec.framework == "generative":
            from kfserving_tpu.predictors.llm import GenerativeModel

            par = getattr(spec, "parallelism", None)
            overrides = {}
            if par is not None and par.chips_per_replica > 1:
                overrides["mesh"] = {
                    "dp": par.dp, "tp": par.tp, "sp": par.sp}
            return GenerativeModel(isvc_name, spec.storage_uri,
                                   config_overrides=overrides)
        if spec.framework == "sklearn":
            from kfserving_tpu.predictors.sklearnserver import SKLearnModel

            return SKLearnModel(isvc_name, spec.storage_uri)
        if spec.framework == "xgboost":
            from kfserving_tpu.predictors.xgbserver import XGBoostModel

            return XGBoostModel(isvc_name, spec.storage_uri)
        if spec.framework == "lightgbm":
            from kfserving_tpu.predictors.lgbserver import LightGBMModel

            return LightGBMModel(isvc_name, spec.storage_uri)
        if spec.framework == "pmml":
            from kfserving_tpu.predictors.pmmlserver import PMMLModel

            return PMMLModel(isvc_name, spec.storage_uri)
        if spec.framework == "pytorch":
            from kfserving_tpu.predictors.torchserver import PyTorchModel

            return PyTorchModel(isvc_name, spec.storage_uri)
        raise ValueError(
            f"in-process orchestrator cannot run framework "
            f"{spec.framework!r}")
    if isinstance(spec, ExplainerSpec):
        from kfserving_tpu.explainers import build_explainer

        return build_explainer(isvc_name, spec.explainer_type,
                               spec.storage_uri)
    if isinstance(spec, TransformerSpec):
        raise ValueError(
            "transformer replicas need a custom model_factory (their "
            "preprocess code is user-supplied)")
    raise ValueError(f"unknown component spec {type(spec).__name__}")
