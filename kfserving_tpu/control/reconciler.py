"""InferenceService reconciler: spec -> replicas + routes + status.

Reference shape (pkg/controller/v1beta1/inferenceservice/
controller.go:68-161): per-component reconcile, then ingress, then status
conditions; canary is two revisions with a traffic split
(ksvc_reconciler.go:84-118); status tracks previous-ready revision for
rollback (inference_service_status.go:47-70).

The TPU reconciler is the same loop without Kubernetes: revisions are
content hashes of the component spec; the previous revision's replicas
are kept alive while canary_traffic_percent routes a slice of traffic to
the new one; promoting (canary=None) or rolling back (reverting the spec)
garbage-collects the losing revision.
"""

import hashlib
import json
import logging
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from kfserving_tpu.control.defaults import apply_defaults
from kfserving_tpu.control.spec import ComponentSpec, InferenceService
from kfserving_tpu.control.topology import select_topology
from kfserving_tpu.control.validation import validate

logger = logging.getLogger("kfserving_tpu.control.reconciler")


# Fields that configure traffic/scaling policy, not the served artifact:
# changing them must not mint a new revision (Knative hashes the pod spec;
# traffic split and autoscaling bounds live outside it).
_POLICY_FIELDS = ("canary_traffic_percent", "min_replicas", "max_replicas")


def revision_of(component: ComponentSpec) -> str:
    """Content-addressed revision id (replaces Knative revision names)."""
    d = asdict(component)
    for f in _POLICY_FIELDS:
        d.pop(f, None)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


@dataclass
class TrafficTarget:
    revision: str
    percent: int
    tag: str = ""  # "prev" for the canary's stable side


@dataclass
class ComponentStatus:
    ready: bool = False
    latest_revision: str = ""
    previous_revision: str = ""
    traffic: List[TrafficTarget] = field(default_factory=list)
    replicas: int = 0
    placement: Optional[object] = None  # latest revision's SlicePlacement
    # Placement per revision: during a canary the previous revision keeps
    # the slice shape it was resolved with (its parallelism may differ
    # from the latest spec's).
    placements: Dict[str, object] = field(default_factory=dict)


@dataclass
class IsvcStatus:
    components: Dict[str, ComponentStatus] = field(default_factory=dict)
    conditions: Dict[str, bool] = field(default_factory=dict)

    @property
    def ready(self) -> bool:
        return bool(self.conditions) and all(self.conditions.values())


class InferenceServiceReconciler:
    def __init__(self, orchestrator):
        self.orchestrator = orchestrator
        self.status: Dict[str, IsvcStatus] = {}

    @staticmethod
    def component_id(isvc: InferenceService, component: str) -> str:
        return f"{isvc.namespace}/{isvc.name}/{component}"

    async def reconcile(self, isvc: InferenceService) -> IsvcStatus:
        apply_defaults(isvc)
        validate(isvc)
        key = f"{isvc.namespace}/{isvc.name}"
        status = self.status.setdefault(key, IsvcStatus())

        for cname, comp in isvc.components().items():
            cstatus = status.components.setdefault(cname, ComponentStatus())
            await self._reconcile_component(isvc, cname, comp, cstatus)
            status.conditions[f"{cname}Ready"] = cstatus.ready
        # Drop components removed from the spec.
        for gone in set(status.components) - set(isvc.components()):
            await self._scale_revisions(
                self.component_id(isvc, gone), {}, None)
            del status.components[gone]
            status.conditions.pop(f"{gone}Ready", None)
        return status

    async def delete(self, isvc: InferenceService) -> None:
        """Finalizer: tear down all components (reference
        controller.go:208-223 deletes child resources)."""
        for cname in list(isvc.components()):
            await self._scale_revisions(
                self.component_id(isvc, cname), {}, None)
        self.status.pop(f"{isvc.namespace}/{isvc.name}", None)

    # -- internals ---------------------------------------------------------
    async def _reconcile_component(self, isvc: InferenceService,
                                   cname: str, comp: ComponentSpec,
                                   cstatus: ComponentStatus) -> None:
        cid = self.component_id(isvc, cname)
        new_rev = revision_of(comp)
        # Slice topology resolution (the accelerator-injector step,
        # reference mutator.go:113-117 chain): chip-owning predictors get
        # a placement, everything else None.
        cstatus.placement = select_topology(comp, isvc.annotations)
        cstatus.placements[new_rev] = cstatus.placement

        if cstatus.latest_revision and cstatus.latest_revision != new_rev:
            cstatus.previous_revision = cstatus.latest_revision
        cstatus.latest_revision = new_rev

        canary = comp.canary_traffic_percent
        base = (max(comp.min_replicas, 1)
                if comp.min_replicas > 0 or canary is not None
                else comp.min_replicas)
        # Re-applying an unchanged revision must not undo autoscaling: the
        # reconciler owns the floor, the autoscaler owns anything above it
        # (clamped to max_replicas).
        current = sum(1 for r in self.orchestrator.replicas(cid)
                      if r.revision == new_rev)
        desired: Dict[str, int] = {
            new_rev: min(max(base, current), max(comp.max_replicas, base))}
        if canary is not None and cstatus.previous_revision and \
                cstatus.previous_revision != new_rev:
            # Canary: previous revision keeps serving (reference keeps the
            # `prev` TrafficTarget, ksvc_reconciler.go:92-118).
            desired[cstatus.previous_revision] = max(comp.min_replicas, 1)
            cstatus.traffic = [
                TrafficTarget(new_rev, canary),
                TrafficTarget(cstatus.previous_revision, 100 - canary,
                              tag="prev"),
            ]
        else:
            cstatus.traffic = [TrafficTarget(new_rev, 100)]
            if canary is None:
                cstatus.previous_revision = ""

        # Revisions no longer desired also drop their recorded placement.
        for rev in set(cstatus.placements) - set(desired):
            del cstatus.placements[rev]
        await self._scale_revisions(cid, desired, comp,
                                    placements=cstatus.placements)
        replicas = self.orchestrator.replicas(cid)
        cstatus.replicas = len(replicas)
        cstatus.ready = all(
            desired.get(rev, 0) <= sum(
                1 for r in replicas if r.revision == rev)
            for rev in desired) and cstatus.replicas > 0

    async def _scale_revisions(self, cid: str,
                               desired: Dict[str, int],
                               comp: Optional[ComponentSpec],
                               placements: Optional[Dict] = None) -> None:
        """Converge the orchestrator's replicas to `desired` (rev->count).

        placements maps revision -> SlicePlacement: a canary's previous
        revision scales with the slice shape it was resolved with, never
        the latest spec's.
        """
        placements = placements or {}
        current = self.orchestrator.replicas(cid)
        by_rev: Dict[str, List] = {}
        for r in current:
            by_rev.setdefault(r.revision, []).append(r)
        # scale down / remove dead revisions
        for rev, replicas in by_rev.items():
            want = desired.get(rev, 0)
            for replica in replicas[want:]:
                await self.orchestrator.delete_replica(replica)
        # scale up — counting creates already in flight (an orchestrator
        # swapping/recycling a replica registers it only when ready; a
        # second spawn in that window would double-own a TPU chip).
        pending = getattr(self.orchestrator, "pending_creates",
                          lambda cid_, rev_: 0)
        for rev, want in desired.items():
            have = len(by_rev.get(rev, [])) + pending(cid, rev)
            for _ in range(max(0, want - have)):
                await self.orchestrator.create_replica(
                    cid, rev, comp, placement=placements.get(rev))

    async def scale(self, isvc: InferenceService, cname: str,
                    replicas: int) -> None:
        """Autoscaler entry: resize the latest revision within bounds."""
        comp = isvc.components()[cname]
        replicas = max(comp.min_replicas,
                       min(comp.max_replicas, replicas))
        cid = self.component_id(isvc, cname)
        key = f"{isvc.namespace}/{isvc.name}"
        cstatus = self.status[key].components[cname]
        desired = {t.revision: replicas for t in cstatus.traffic
                   if t.percent > 0}
        # revisions with zero traffic keep zero replicas
        await self._scale_revisions(cid, desired, comp,
                                    placements=cstatus.placements)
        cstatus.replicas = len(self.orchestrator.replicas(cid))
