"""InferenceService reconciler: spec -> replicas + routes + status.

Reference shape (pkg/controller/v1beta1/inferenceservice/
controller.go:68-161): per-component reconcile, then ingress, then status
conditions; canary is two revisions with a traffic split
(ksvc_reconciler.go:84-118); status tracks previous-ready revision for
rollback (inference_service_status.go:47-70).

The TPU reconciler is the same loop without Kubernetes: revisions are
content hashes of the component spec; the previous revision's replicas
are kept alive while canary_traffic_percent routes a slice of traffic to
the new one; promoting (canary=None) or rolling back (reverting the spec)
garbage-collects the losing revision.
"""

import copy
import hashlib
import json
import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from kfserving_tpu.control.defaults import apply_defaults
from kfserving_tpu.control.spec import ComponentSpec, InferenceService
from kfserving_tpu.control.topology import select_topology
from kfserving_tpu.control.validation import validate

logger = logging.getLogger("kfserving_tpu.control.reconciler")


# Fields that configure traffic/scaling policy, not the served artifact:
# changing them must not mint a new revision (Knative hashes the pod spec;
# traffic split and autoscaling bounds live outside it).  The rollout
# policy is pure traffic policy too — retuning a step schedule must not
# re-roll the model.
_POLICY_FIELDS = ("canary_traffic_percent", "min_replicas", "max_replicas",
                  "rollout")


def revision_of(component: ComponentSpec) -> str:
    """Content-addressed revision id (replaces Knative revision names)."""
    d = asdict(component)
    for f in _POLICY_FIELDS:
        d.pop(f, None)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


@dataclass
class TrafficTarget:
    revision: str
    percent: int
    tag: str = ""  # "prev" for the canary's stable side


@dataclass
class ComponentStatus:
    ready: bool = False
    latest_revision: str = ""
    previous_revision: str = ""
    traffic: List[TrafficTarget] = field(default_factory=list)
    replicas: int = 0
    placement: Optional[object] = None  # latest revision's SlicePlacement
    # Placement per revision: during a canary the previous revision keeps
    # the slice shape it was resolved with (its parallelism may differ
    # from the latest spec's).
    placements: Dict[str, object] = field(default_factory=dict)
    # Spec snapshot per live revision: a canary's previous revision (and
    # a rollback's stable revision) must scale with the spec it was
    # APPLIED with — creating a "previous-revision" replica from the
    # latest spec would serve the new artifact under the old label.
    specs: Dict[str, ComponentSpec] = field(default_factory=dict)
    # Set when the applied spec's revision is quarantined and traffic is
    # being substituted to the stable revision instead.
    quarantined_revision: str = ""


@dataclass
class IsvcStatus:
    components: Dict[str, ComponentStatus] = field(default_factory=dict)
    conditions: Dict[str, bool] = field(default_factory=dict)

    @property
    def ready(self) -> bool:
        return bool(self.conditions) and all(self.conditions.values())


class InferenceServiceReconciler:
    def __init__(self, orchestrator):
        self.orchestrator = orchestrator
        self.status: Dict[str, IsvcStatus] = {}
        # Quarantine: component_id -> {bad revision: {stable, reason,
        # ts}}.  A quarantined revision's spec re-applied verbatim is
        # substituted with its recorded stable revision instead of
        # silently re-rolling the exact bytes that just failed a gate.
        self.quarantine: Dict[str, Dict[str, Dict[str, Any]]] = {}

    @staticmethod
    def component_id(isvc: InferenceService, component: str) -> str:
        return f"{isvc.namespace}/{isvc.name}/{component}"

    async def reconcile(self, isvc: InferenceService) -> IsvcStatus:
        apply_defaults(isvc)
        validate(isvc)
        key = f"{isvc.namespace}/{isvc.name}"
        status = self.status.setdefault(key, IsvcStatus())

        for cname, comp in isvc.components().items():
            cstatus = status.components.setdefault(cname, ComponentStatus())
            await self._reconcile_component(isvc, cname, comp, cstatus)
            status.conditions[f"{cname}Ready"] = cstatus.ready
        # Drop components removed from the spec.
        for gone in set(status.components) - set(isvc.components()):
            await self._scale_revisions(
                self.component_id(isvc, gone), {}, None)
            del status.components[gone]
            status.conditions.pop(f"{gone}Ready", None)
        return status

    async def delete(self, isvc: InferenceService) -> None:
        """Finalizer: tear down all components (reference
        controller.go:208-223 deletes child resources)."""
        for cname in list(isvc.components()):
            cid = self.component_id(isvc, cname)
            await self._scale_revisions(cid, {}, None)
            self.quarantine.pop(cid, None)
        self.status.pop(f"{isvc.namespace}/{isvc.name}", None)

    def quarantine_report(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """Serializable copy of the quarantine ledger (the single
        shape GET /v2/rollouts serves, manager-wired or not)."""
        return {cid: {rev: dict(info) for rev, info in revs.items()}
                for cid, revs in self.quarantine.items()}

    async def promote(self, isvc: InferenceService, cname: str) -> None:
        """Terminal canary promotion: the latest revision becomes the
        only traffic target and the previous revision is GC'd in one
        reconcile.  Needed as an explicit verb for rollout-managed
        components: their defaulting pins canary_traffic_percent to a
        managed 0, so a plain re-reconcile would read as a fresh 0%
        canary instead of a finished one."""
        key = f"{isvc.namespace}/{isvc.name}"
        status = self.status.get(key)
        cstatus = status.components.get(cname) if status else None
        if cstatus is None:
            return
        latest = cstatus.latest_revision
        cstatus.previous_revision = ""
        cstatus.traffic = [TrafficTarget(latest, 100)]
        spec = cstatus.specs.get(latest, isvc.components().get(cname))
        current = sum(1 for r in self.orchestrator.replicas(
            self.component_id(isvc, cname)) if r.revision == latest)
        floor = max(getattr(spec, "min_replicas", 1) or 1, 1)
        desired = {latest: max(current, floor)}
        for rev in set(cstatus.placements) - set(desired):
            del cstatus.placements[rev]
        for rev in set(cstatus.specs) - set(desired):
            del cstatus.specs[rev]
        cid = self.component_id(isvc, cname)
        await self._scale_revisions(cid, desired, spec,
                                    placements=cstatus.placements,
                                    specs=cstatus.specs)
        cstatus.placement = cstatus.placements.get(latest)
        cstatus.replicas = len(self.orchestrator.replicas(cid))
        cstatus.ready = cstatus.replicas > 0
        status.conditions[f"{cname}Ready"] = cstatus.ready

    async def rollback(self, isvc: InferenceService, cname: str,
                       reason: str = "gate_failed") -> Optional[str]:
        """Auto-rollback: revert ALL traffic to the stable (previous)
        revision in one reconcile and quarantine the losing revision's
        content hash.  Returns the quarantined revision, or None when
        there is no canary pair to roll back.

        The reference models this as re-routing to the
        previous-ready revision (inference_service_status.go:47-70);
        here the quarantine additionally pins the decision: re-applying
        the identical spec resolves to the stable revision instead of
        silently re-rolling the bytes that just failed."""
        key = f"{isvc.namespace}/{isvc.name}"
        status = self.status.get(key)
        cstatus = status.components.get(cname) if status else None
        if cstatus is None:
            return None
        bad = cstatus.latest_revision
        stable = cstatus.previous_revision
        if not stable or stable == bad:
            return None
        cid = self.component_id(isvc, cname)
        self.quarantine.setdefault(cid, {})[bad] = {
            "stable": stable, "reason": reason, "ts": time.time()}
        stable_spec = cstatus.specs.get(stable)
        cstatus.latest_revision = stable
        cstatus.previous_revision = ""
        cstatus.quarantined_revision = bad
        cstatus.traffic = [TrafficTarget(stable, 100)]
        desired = {stable: max(getattr(stable_spec, "min_replicas", 1)
                               or 1, 1)}
        for rev in set(cstatus.placements) - set(desired):
            del cstatus.placements[rev]
        for rev in set(cstatus.specs) - set(desired):
            del cstatus.specs[rev]
        await self._scale_revisions(cid, desired, stable_spec,
                                    placements=cstatus.placements,
                                    specs=cstatus.specs)
        cstatus.placement = cstatus.placements.get(stable)
        replicas = self.orchestrator.replicas(cid)
        cstatus.replicas = len(replicas)
        cstatus.ready = cstatus.replicas > 0
        status.conditions[f"{cname}Ready"] = cstatus.ready
        logger.warning("rolled back %s: revision %s quarantined (%s), "
                       "traffic reverted to %s", cid, bad, reason,
                       stable)
        return bad

    # -- internals ---------------------------------------------------------
    async def _reconcile_component(self, isvc: InferenceService,
                                   cname: str, comp: ComponentSpec,
                                   cstatus: ComponentStatus) -> None:
        cid = self.component_id(isvc, cname)
        new_rev = revision_of(comp)
        quarantined = self.quarantine.get(cid, {}).get(new_rev)
        cstatus.quarantined_revision = ""
        if quarantined is not None:
            # Re-apply of a rolled-back revision: serve a known-good
            # spec instead (content hash remembered — the identical
            # bytes do not re-roll).  A genuinely NEW revision clears
            # this path by hashing differently.  Preferred substitute
            # is the rollback's recorded stable; when its snapshot has
            # since been GC'd (a fixed revision promoted in between),
            # whatever is live now is the stable — the quarantine must
            # outlive any one snapshot.
            substitute = quarantined["stable"]
            if substitute not in cstatus.specs:
                substitute = cstatus.latest_revision
            sub_spec = cstatus.specs.get(substitute)
            if sub_spec is not None:
                logger.warning(
                    "revision %s of %s is quarantined (%s); keeping "
                    "revision %s", new_rev, cid,
                    quarantined.get("reason", "rolled back"),
                    substitute)
                cstatus.quarantined_revision = new_rev
                comp = copy.deepcopy(sub_spec)
                comp.canary_traffic_percent = None
                new_rev = substitute
            else:
                logger.error(
                    "revision %s of %s is quarantined but no live "
                    "spec snapshot exists to substitute; serving it "
                    "anyway", new_rev, cid)
        # Slice topology resolution (the accelerator-injector step,
        # reference mutator.go:113-117 chain): chip-owning predictors get
        # a placement, everything else None.
        cstatus.placement = select_topology(comp, isvc.annotations)
        cstatus.placements[new_rev] = cstatus.placement
        cstatus.specs[new_rev] = copy.deepcopy(comp)

        if cstatus.latest_revision and cstatus.latest_revision != new_rev:
            cstatus.previous_revision = cstatus.latest_revision
        cstatus.latest_revision = new_rev

        canary = comp.canary_traffic_percent
        base = (max(comp.min_replicas, 1)
                if comp.min_replicas > 0 or canary is not None
                else comp.min_replicas)
        # Re-applying an unchanged revision must not undo autoscaling: the
        # reconciler owns the floor, the autoscaler owns anything above it
        # (clamped to max_replicas).
        current = sum(1 for r in self.orchestrator.replicas(cid)
                      if r.revision == new_rev)
        desired: Dict[str, int] = {
            new_rev: min(max(base, current), max(comp.max_replicas, base))}
        if canary is not None and cstatus.previous_revision and \
                cstatus.previous_revision != new_rev:
            # Canary: previous revision keeps serving (reference keeps the
            # `prev` TrafficTarget, ksvc_reconciler.go:92-118), sized by
            # ITS spec snapshot, not the canary's.
            prev_spec = cstatus.specs.get(cstatus.previous_revision, comp)
            desired[cstatus.previous_revision] = \
                max(prev_spec.min_replicas, 1)
            cstatus.traffic = [
                TrafficTarget(new_rev, canary),
                TrafficTarget(cstatus.previous_revision, 100 - canary,
                              tag="prev"),
            ]
        else:
            cstatus.traffic = [TrafficTarget(new_rev, 100)]
            if canary is None:
                cstatus.previous_revision = ""

        # Revisions no longer desired also drop their recorded placement
        # and spec snapshot.
        for rev in set(cstatus.placements) - set(desired):
            del cstatus.placements[rev]
        for rev in set(cstatus.specs) - set(desired):
            del cstatus.specs[rev]
        await self._scale_revisions(cid, desired, comp,
                                    placements=cstatus.placements,
                                    specs=cstatus.specs)
        replicas = self.orchestrator.replicas(cid)
        cstatus.replicas = len(replicas)
        cstatus.ready = all(
            desired.get(rev, 0) <= sum(
                1 for r in replicas if r.revision == rev)
            for rev in desired) and cstatus.replicas > 0

    async def _scale_revisions(self, cid: str,
                               desired: Dict[str, int],
                               comp: Optional[ComponentSpec],
                               placements: Optional[Dict] = None,
                               specs: Optional[Dict] = None) -> None:
        """Converge the orchestrator's replicas to `desired` (rev->count).

        placements maps revision -> SlicePlacement and specs maps
        revision -> ComponentSpec snapshot: a canary's previous (or a
        rollback's stable) revision scales with the slice shape AND the
        spec it was applied with, never the latest spec's — a replica
        labeled with the old revision must serve the old artifact.
        """
        placements = placements or {}
        specs = specs or {}
        current = self.orchestrator.replicas(cid)
        by_rev: Dict[str, List] = {}
        for r in current:
            by_rev.setdefault(r.revision, []).append(r)
        # scale down / remove dead revisions — including any armed
        # warm standby of a revision that stops serving entirely (a
        # retired canary's standby surviving to be crash-promoted
        # later would resurrect the exact revision this scale-down
        # removes).
        reap = getattr(self.orchestrator, "reap_standbys", None)
        for rev, replicas in by_rev.items():
            want = desired.get(rev, 0)
            for replica in replicas[want:]:
                await self.orchestrator.delete_replica(replica)
            if want == 0 and reap is not None:
                await reap(cid, rev)
        if not desired and reap is not None:
            await reap(cid)
        # scale up — counting creates already in flight (an orchestrator
        # swapping/recycling a replica registers it only when ready; a
        # second spawn in that window would double-own a TPU chip).
        # Orchestrators with an armed-standby pool satisfy the
        # increment by ACTIVATING a standby first (one-tick promotion,
        # the PR 7 actuator the predictive autoscaler pre-arms for) —
        # only when the pool is dry does the cold spawn pay its price.
        pending = getattr(self.orchestrator, "pending_creates",
                          lambda cid_, rev_: 0)
        adopt = getattr(self.orchestrator, "adopt_standby", None)
        for rev, want in desired.items():
            have = len(by_rev.get(rev, [])) + pending(cid, rev)
            for _ in range(max(0, want - have)):
                if adopt is not None and \
                        await adopt(cid, rev) is not None:
                    continue
                await self.orchestrator.create_replica(
                    cid, rev, specs.get(rev, comp),
                    placement=placements.get(rev))

    async def scale(self, isvc: InferenceService, cname: str,
                    replicas: int) -> None:
        """Autoscaler entry: resize the latest revision within bounds."""
        comp = isvc.components()[cname]
        replicas = max(comp.min_replicas,
                       min(comp.max_replicas, replicas))
        cid = self.component_id(isvc, cname)
        key = f"{isvc.namespace}/{isvc.name}"
        cstatus = self.status[key].components[cname]
        desired = {t.revision: replicas for t in cstatus.traffic
                   if t.percent > 0}
        # Any 0% traffic target keeps a floor of replicas: a
        # warmup-gated canary is waiting to become ready (scaling it
        # away deadlocks the first step), and the stable side of a
        # 100% final step is the rollback target (scaling it away
        # turns a last-gate rollback into a cold-start outage).
        for t in cstatus.traffic:
            if t.percent == 0:
                spec = cstatus.specs.get(t.revision, comp)
                desired.setdefault(t.revision,
                                   max(spec.min_replicas, 1))
        await self._scale_revisions(cid, desired, comp,
                                    placements=cstatus.placements,
                                    specs=cstatus.specs)
        cstatus.replicas = len(self.orchestrator.replicas(cid))
