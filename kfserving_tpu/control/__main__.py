"""`python -m kfserving_tpu.control serve` — the manager entrypoint
(reference cmd/manager/main.go:59-186)."""

import argparse
import logging

from kfserving_tpu.control.clusterconfig import ClusterConfig
from kfserving_tpu.control.manager import ServingManager

parser = argparse.ArgumentParser(prog="kfserving_tpu.control")
sub = parser.add_subparsers(dest="command", required=True)

serve = sub.add_parser("serve", help="run the serving fabric")
serve.add_argument("--config", default=None,
                   help="cluster config JSON (tier-1; defaults if absent)")
serve.add_argument("--control-port", type=int, default=8081,
                   help="control API port (the apiserver surface)")
serve.add_argument("--ingress-port", type=int, default=None,
                   help="data-plane ingress port (default: the cluster "
                        "config's ingress block, else 8080)")
serve.add_argument("--host", default=None,
                   help="bind address (default: cluster config ingress "
                        "host, else 127.0.0.1)")
serve.add_argument("--orchestrator", default="inprocess",
                   choices=["inprocess", "subprocess"],
                   help="replica actuation backend")
serve.add_argument("--apply", action="append", default=[],
                   help="InferenceService spec file(s) to apply at boot")
serve.add_argument("--log-level", default="INFO")


def main(argv=None):
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    if args.command == "serve":
        manager = ServingManager(
            cluster_config=ClusterConfig.load(args.config),
            orchestrator=args.orchestrator,
            control_port=args.control_port,
            ingress_port=args.ingress_port,
            host=args.host)
        manager.run(apply=args.apply)


if __name__ == "__main__":
    main()
