"""HBM-aware shard assignment for multi-model serving.

The reference's strategy interface has exactly one implementation: a stub
that puts every model on shard 0 (reference pkg/controller/v1alpha1/
trainedmodel/sharding/memory/strategy.go:29-39), with the TrainedModel's
declared Memory unused.  SURVEY.md §7 names real HBM-aware sharding a
north-star item; this is it:

- each shard is one predictor replica-set with an HBM budget (chip HBM x
  chips_per_replica minus runtime headroom);
- placement is first-fit-decreasing bin packing on declared memory_bytes —
  FFD is within 22% of optimal and, more importantly here, deterministic
  and stable under incremental adds;
- existing placements are sticky (a re-reconcile never migrates a model
  that still fits), because moving a model = recompiling its executables.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kfserving_tpu.control.spec import TrainedModel


class ShardingError(ValueError):
    pass


@dataclass
class Shard:
    index: int
    budget_bytes: int
    models: Dict[str, int] = field(default_factory=dict)  # name -> bytes

    @property
    def used_bytes(self) -> int:
        return sum(self.models.values())

    @property
    def free_bytes(self) -> int:
        return self.budget_bytes - self.used_bytes


class HBMShardStrategy:
    """Assign TrainedModels to shards within an HBM budget per shard.

    max_shards bounds the fleet (a shard is a whole serving replica-set);
    growing past it raises, mirroring the admission error a user sees when
    a TrainedModel can't fit (reference surfaces this via the TrainedModel
    Ready condition)."""

    def __init__(self, shard_budget_bytes: int, max_shards: int = 8):
        if shard_budget_bytes <= 0:
            raise ValueError("shard_budget_bytes must be > 0")
        self.shard_budget_bytes = shard_budget_bytes
        self.max_shards = max_shards
        self.shards: List[Shard] = []
        self._placement: Dict[str, int] = {}

    # -- queries -----------------------------------------------------------
    def get_shard(self, model_name: str) -> Optional[int]:
        return self._placement.get(model_name)

    def models_on(self, shard_index: int) -> List[str]:
        return sorted(self.shards[shard_index].models)

    # -- assignment --------------------------------------------------------
    def get_or_assign(self, tm: TrainedModel) -> int:
        """Sticky first-fit: an existing placement is kept; a new model
        goes to the first shard with room, else a new shard."""
        existing = self._placement.get(tm.name)
        if existing is not None:
            shard = self.shards[existing]
            old = shard.models[tm.name]
            if tm.memory_bytes <= shard.free_bytes + old:
                shard.models[tm.name] = tm.memory_bytes
                return existing
            # grew past its shard: remove and re-place
            del shard.models[tm.name]
            del self._placement[tm.name]
        if tm.memory_bytes > self.shard_budget_bytes:
            raise ShardingError(
                f"model {tm.name} declares {tm.memory_bytes} bytes; a "
                f"shard holds {self.shard_budget_bytes}")
        for shard in self.shards:
            if tm.memory_bytes <= shard.free_bytes:
                shard.models[tm.name] = tm.memory_bytes
                self._placement[tm.name] = shard.index
                return shard.index
        if len(self.shards) >= self.max_shards:
            raise ShardingError(
                f"model {tm.name} does not fit in any of "
                f"{self.max_shards} shards")
        shard = Shard(index=len(self.shards),
                      budget_bytes=self.shard_budget_bytes)
        shard.models[tm.name] = tm.memory_bytes
        self.shards.append(shard)
        self._placement[tm.name] = shard.index
        return shard.index

    def remove(self, model_name: str) -> Optional[int]:
        idx = self._placement.pop(model_name, None)
        if idx is not None:
            self.shards[idx].models.pop(model_name, None)
        return idx

    def pack(self, models: List[TrainedModel]) -> Dict[str, int]:
        """Batch placement, first-fit-decreasing (initial reconcile)."""
        for tm in sorted(models, key=lambda m: -m.memory_bytes):
            self.get_or_assign(tm)
        return dict(self._placement)

    def stats(self) -> List[dict]:
        return [{"shard": s.index, "used": s.used_bytes,
                 "free": s.free_bytes, "models": len(s.models)}
                for s in self.shards]
