"""SubprocessOrchestrator: replicas are real OS processes.

The reference's replicas are pods created by Knative from the ksvc the
reconciler writes (reference ksvc_reconciler.go:153-187); the
single-host TPU equivalent is one process per replica, exec'd from the
per-framework entrypoint module registered in the cluster config
(`python -m kfserving_tpu.predictors.<fw> --model_name ... --model_dir
... --http_port ...` — the same arg convention the reference's
predictor specs build, predictor_sklearn.go:77-96).

Readiness mirrors the pod readiness probe: the replica joins the
router's rotation only after its health route answers.  Deletion is
SIGTERM (the server's signal handler drains in-flight work) escalating
to SIGKILL.

TPU note: on a single chip only one process can own the device; either
give each JAX replica a distinct mesh slice via env (TPU_VISIBLE_DEVICES
/ JAX_PLATFORMS) through `env_overrides`, or keep max_replicas=1 for
chip-owning predictors.  CPU frameworks (sklearn/xgb/...) scale freely.
"""

import asyncio
import logging
import os
import socket
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kfserving_tpu.control.clusterconfig import ClusterConfig
from kfserving_tpu.control.orchestrator import Replica, _ComponentState

logger = logging.getLogger("kfserving_tpu.control.subprocess")

READY_TIMEOUT_S = 120.0
TERM_GRACE_S = 10.0


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@dataclass
class RecyclePolicy:
    """Replica process recycling (ROOFLINE.md soak: the tunneled device
    transport leaks ~3.2 GB/min under load; the pod-level analogue is
    kubelet restarting a container that crosses its memory limit —
    SURVEY.md §5.3 delegation, built natively here).

    A replica crossing either threshold is drain-replaced: a successor
    is spawned (before the drain when `overlap`, after otherwise) and
    the old process gets SIGTERM (the server's handler drains in-flight
    work).  The router's readiness gating + scale-from-zero buffering
    carry traffic across the swap.

    overlap=True is the zero-gap swap: the successor fully loads
    (device init + compile + warmup) while the old replica still
    serves; downtime is only the rotation switch.  It requires the
    device transport to admit two resident processes — true for CPU
    replicas, and MEASURED true for the tunneled chip this repo
    benches on (two processes ran synchronized matmuls concurrently;
    the r2/r3 "one process owns the TPU" premise does not hold on this
    transport).  Transient HBM cost: both generations resident.

    overlap=False is for exclusive-device deployments (real TPU pods,
    where libtpu locks the chip): the successor can't initialize until
    the old owner exits.  There the orchestrator uses the STANDBY
    fast-swap (KFS_STANDBY + /standby/activate): interpreter start,
    imports, and artifact download happen outside the gap, so the
    window is device init + cache-hot compile + warmup only.
    """

    max_requests: Optional[int] = None
    max_rss_mb: Optional[float] = None
    check_interval_s: float = 5.0
    overlap: bool = True
    # Successor grace: a replica younger than this is never recycled.
    # Without it, a threshold at/below a fresh process's baseline RSS
    # (easy with JAX loaded) would kill/spawn in an unbounded loop with
    # a zero-replica gap per cycle on chip owners.
    min_age_s: float = 30.0
    # Overlapped successors load at this nice level and are restored to
    # 0 once serving.  On a small host the successor's XLA
    # compile/deserialize otherwise starves the OLD replica's event
    # loop for the whole load — measured soak p99 went 0.7s -> 27s from
    # CPU contention alone, with zero unavailability.
    successor_nice: int = 15


def _proc_rss_mb(pid: int) -> Optional[float]:
    """Resident set size of a pid in MB (Linux /proc, no psutil)."""
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        return None
    return None


@dataclass
class _Proc:
    process: asyncio.subprocess.Process
    port: int
    spec: object = None
    spawned_at: float = 0.0


class SubprocessOrchestrator:
    """Actuation backend that execs one server process per replica."""

    def __init__(self, cluster_config: Optional[ClusterConfig] = None,
                 env_overrides: Optional[Dict[str, str]] = None,
                 host: str = "127.0.0.1",
                 credentials=None,
                 recycle: Optional[RecyclePolicy] = None):
        self.cluster_config = cluster_config or ClusterConfig()
        self.env_overrides = env_overrides or {}
        self.host = host
        # CredentialStore: per-service-account env injected into replica
        # processes (reference credential builder injects into containers).
        self.credentials = credentials
        self.recycle = recycle
        self.recycle_count = 0
        # Chip-release -> successor-serving gap of each overlap=False
        # swap (the soak's swap_window_s stat; VERDICT r3 weak #1).
        self.swap_windows_s: List[float] = []
        self.standby_swaps = 0
        # Per-swap phase timing: {"standby_spawn_s", "drain_s",
        # "activate_s"} — which part of the window to attack next.
        self.swap_breakdown: List[Dict[str, float]] = []
        self._watchdog: Optional[asyncio.Task] = None
        self._recycling: set = set()  # replica ids being swapped
        # (cid, revision) -> count of creates past spawn but not yet
        # ready.  replicas() lists only ready processes, so without this
        # the reconciler's scale-up and the recycler would both spawn
        # during a swap window — fatal for chip-owning replicas (one
        # process per TPU).
        self._creating: Dict[tuple, int] = {}
        self.state: Dict[str, _ComponentState] = {}
        # Cluster-local gateway address, published by the ingress router
        # at start (router.py start_async); replicas get it as
        # KFS_CLUSTER_LOCAL_URL.
        self.cluster_local_url: Optional[str] = None

    def pending_creates(self, component_id: str, revision: str) -> int:
        return self._creating.get((component_id, revision), 0)

    def replicas(self, component_id: str) -> List[Replica]:
        return list(self.state.get(component_id,
                                   _ComponentState()).replicas)

    # -- spec -> argv -------------------------------------------------------
    def _command(self, component_id: str, spec, port: int) -> List[str]:
        from kfserving_tpu.control.spec import (
            ExplainerSpec,
            PredictorSpec,
            TransformerSpec,
        )

        isvc_name = component_id.split("/")[1]
        if isinstance(spec, (TransformerSpec, ExplainerSpec)) and \
                getattr(spec, "command", None):
            return list(spec.command) + ["--http_port", str(port)]
        if isinstance(spec, ExplainerSpec):
            # In-tree explainer types run via the standalone explainer
            # server (the reference's per-explainer binaries,
            # alibiexplainer/__main__.py); predictor_host arrives via
            # the injected KFS_CLUSTER_LOCAL_URL.  Unknown types must
            # fail HERE with a clear error — the child's stderr goes to
            # DEVNULL, so an argparse rejection would surface only as
            # an opaque readiness failure.
            from kfserving_tpu.explainers import (
                ARTIFACT_REQUIRED_TYPES,
                EXPLAINER_TYPES,
            )

            if spec.explainer_type not in EXPLAINER_TYPES:
                raise ValueError(
                    f"explainer_type {spec.explainer_type!r} needs an "
                    f"explicit command under the subprocess "
                    f"orchestrator (in-tree: {list(EXPLAINER_TYPES)})")
            if spec.explainer_type in ARTIFACT_REQUIRED_TYPES and \
                    not spec.storage_uri:
                # Without the artifact dir the child dies in
                # Storage.download with stderr discarded.
                raise ValueError(
                    f"{spec.explainer_type} explainer needs a "
                    f"storage_uri")
            argv = [sys.executable, "-m", "kfserving_tpu.explainers",
                    "--model_name", isvc_name,
                    "--explainer_type", spec.explainer_type,
                    "--http_port", str(port)]
            if spec.storage_uri:
                argv += ["--storage_uri", spec.storage_uri]
            if spec.container_concurrency:
                argv += ["--container_concurrency",
                         str(spec.container_concurrency)]
            return argv
        if isinstance(spec, PredictorSpec):
            if spec.framework == "custom":
                if not spec.command:
                    raise ValueError(
                        "custom predictor needs an explicit command")
                return list(spec.command) + ["--http_port", str(port)]
            from kfserving_tpu.control.spec import (
                EXTERNAL_RUNTIME_FRAMEWORKS,
            )

            if spec.framework in EXTERNAL_RUNTIME_FRAMEWORKS:
                return self._external_command(component_id, spec, port)
            runtime = self.cluster_config.runtime_for(spec.framework)
            argv = [sys.executable, "-m", runtime["module"],
                    "--model_name", isvc_name,
                    "--model_dir", spec.storage_uri,
                    "--http_port", str(port)]
            if spec.container_concurrency:
                argv += ["--container_concurrency",
                         str(spec.container_concurrency)]
            if spec.batcher is not None:
                argv += ["--max_batch_size",
                         str(spec.batcher.max_batch_size),
                         "--max_latency_ms",
                         str(spec.batcher.max_latency_ms)]
            if spec.multi_model:
                argv += ["--multi_model"]
            return argv
        raise ValueError(
            f"subprocess orchestrator cannot run component spec "
            f"{type(spec).__name__} without an explicit command")

    def _external_command(self, component_id: str, spec,
                          port: int) -> List[str]:
        """argv for an external server runtime, per that runtime's own
        CLI convention — the reference builds the same argument lists
        into its container specs (predictor_tfserving.go:84-90,
        predictor_triton.go:59-67, predictor_onnxruntime.go:67-72).
        The binary comes from the cluster config's `command` entry
        (spec.command overrides it, e.g. a site wrapper script)."""
        isvc_name = component_id.split("/")[1]
        runtime = self.cluster_config.runtime_for(spec.framework)
        base = list(spec.command or runtime.get("command") or ())
        if not base:
            raise ValueError(
                f"framework {spec.framework!r} needs a configured "
                f"external server command (cluster config predictors."
                f"{spec.framework}.command)")
        if not spec.storage_uri:
            raise ValueError(
                f"{spec.framework} predictor needs a storage_uri")
        model_dir = spec.storage_uri
        for prefix in ("file://",):
            if model_dir.startswith(prefix):
                model_dir = model_dir[len(prefix):]
        style = runtime.get("argStyle", spec.framework)
        if style == "tfserving":
            return base + [
                f"--rest_api_port={port}",
                f"--model_name={isvc_name}",
                f"--model_base_path={model_dir}",
            ]
        if style == "triton":
            return base + [
                f"--model-store={model_dir}",
                f"--http-port={port}",
                "--allow-http=true",
            ]
        if style == "onnx":
            return base + [
                f"--model_path={model_dir}",
                f"--http_port={port}",
            ]
        raise ValueError(f"unknown external argStyle {style!r}")

    # -- lifecycle ----------------------------------------------------------
    def _standby_capable(self, spec) -> bool:
        """Standby fast-swap needs the runtime to honor KFS_STANDBY
        (deferred device-touching load behind POST /standby/activate) —
        the chip-owning in-tree servers do."""
        from kfserving_tpu.control.spec import PredictorSpec

        return (isinstance(spec, PredictorSpec)
                and spec.framework in ("jax", "generative")
                and not getattr(spec, "multi_model", False))

    async def create_replica(self, component_id: str, revision: str,
                             spec, placement=None,
                             standby: bool = False,
                             nice: int = 0,
                             minimal_warmup: bool = False) -> Replica:
        port = _free_port(self.host)
        argv = self._command(component_id, spec, port)
        env = dict(os.environ)
        if standby:
            env["KFS_STANDBY"] = "1"
        if minimal_warmup or standby:
            # Recycle successors (and standby activations, whose
            # warmup sits inside the exclusive-device swap gap) warm
            # only the largest bucket: the predecessor populated the
            # persistent compile cache, so the rest load on demand —
            # the full grid was the dominant term of successor load
            # time (r5 SOAK successor_phases).
            env["KFS_MINIMAL_WARMUP"] = "1"
        else:
            # A cold first replica (empty persistent cache) must do
            # the full grid; never inherit a stray flag from the
            # orchestrator's own environment.
            env.pop("KFS_MINIMAL_WARMUP", None)
        # The package must be importable from the child even when not
        # pip-installed.
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (
            repo_root + os.pathsep + env.get("PYTHONPATH", "")).rstrip(
                os.pathsep)
        if self.credentials is not None:
            env.update(self.credentials.build_env(
                getattr(spec, "service_account_name", "default")))
        if placement is not None:
            # Slice discovery env — the TPU analogue of the reference's
            # injected nodeSelector (accelerator_injector.go:38-44).
            env.update(placement.env())
        if self.cluster_local_url:
            # Custom explainer/transformer commands reach the predictor
            # through the gateway's direct lane (the reference injects
            # --predictor_host into those containers).
            env["KFS_CLUSTER_LOCAL_URL"] = self.cluster_local_url
        env.update(self.env_overrides)
        logger.info("spawning replica %s rev=%s: %s",
                    component_id, revision[:8], " ".join(argv))
        key = (component_id, revision)
        self._creating[key] = self._creating.get(key, 0) + 1
        try:
            preexec = None
            if nice > 0:
                def preexec(n=nice):  # runs in the child pre-exec
                    os.nice(n)
            process = await asyncio.create_subprocess_exec(
                *argv, env=env,
                stdout=asyncio.subprocess.DEVNULL,
                stderr=asyncio.subprocess.DEVNULL,
                preexec_fn=preexec)
            host = f"{self.host}:{port}"
            try:
                await self._wait_ready(process, host)
            except Exception:
                await self._terminate(process)
                raise
        finally:
            n = self._creating.get(key, 1) - 1
            if n <= 0:
                self._creating.pop(key, None)
            else:
                self._creating[key] = n
        replica = Replica(component_id, revision, host,
                          handle=_Proc(
                              process, port, spec=spec,
                              spawned_at=asyncio.get_running_loop().time()),
                          placement=placement)
        if standby:
            # Not serving yet: joins `state` (and the router's
            # rotation) only after _activate_standby succeeds.
            return replica
        self.state.setdefault(component_id,
                              _ComponentState()).replicas.append(replica)
        if self.recycle is not None and self._watchdog is None:
            self._watchdog = asyncio.ensure_future(self._watchdog_loop())
        return replica

    async def _activate_standby(self, replica: Replica) -> None:
        """Flip a standby successor live: POST its activation route (the
        deferred device-touching load runs there), then enter it into
        the serving state."""
        import aiohttp

        url = f"http://{replica.host}/standby/activate"
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=READY_TIMEOUT_S)) as session:
            async with session.post(url) as resp:
                body = await resp.text()
                if resp.status != 200:
                    raise RuntimeError(
                        f"standby activation at {replica.host} failed "
                        f"({resp.status}): {body[:500]}")
        self.state.setdefault(replica.component_id,
                              _ComponentState()).replicas.append(replica)
        if self.recycle is not None and self._watchdog is None:
            self._watchdog = asyncio.ensure_future(self._watchdog_loop())

    async def _wait_ready(self, process, host: str) -> None:
        """Poll the liveness route until it answers (readiness probe)."""
        import aiohttp

        deadline = asyncio.get_running_loop().time() + READY_TIMEOUT_S
        url = f"http://{host}/"
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=2.0)) as session:
            while True:
                if process.returncode is not None:
                    raise RuntimeError(
                        f"replica process exited rc={process.returncode} "
                        f"before becoming ready")
                try:
                    async with session.get(url) as resp:
                        if resp.status == 200:
                            return
                except Exception:
                    pass
                if asyncio.get_running_loop().time() > deadline:
                    raise TimeoutError(
                        f"replica at {host} not ready after "
                        f"{READY_TIMEOUT_S}s")
                await asyncio.sleep(0.1)

    # -- recycling ----------------------------------------------------------
    async def _startup_phases(self, host: str) -> Dict[str, float]:
        import aiohttp

        try:
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=2.0)) as session:
                async with session.get(
                        f"http://{host}/startup_phases") as resp:
                    if resp.status == 200:
                        return await resp.json()
        except Exception:
            logger.debug("startup phases scrape of %s failed", host)
        return {}

    async def _request_count(self, host: str) -> Optional[float]:
        """Best-effort scrape of the replica's request counter (the
        server's Prometheus text endpoint)."""
        import aiohttp

        try:
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=2.0)) as session:
                async with session.get(f"http://{host}/metrics") as resp:
                    if resp.status != 200:
                        return None
                    text = await resp.text()
        except Exception:
            return None
        from kfserving_tpu.server.metrics import REQUEST_TOTAL_SERIES

        total = 0.0
        for line in text.splitlines():
            if line.startswith(REQUEST_TOTAL_SERIES + "{"):
                try:
                    total += float(line.rsplit(" ", 1)[1])
                except (IndexError, ValueError):
                    pass
        return total

    def _over_threshold(self, handle: _Proc) -> Optional[str]:
        pol = self.recycle
        if pol.max_rss_mb is not None and handle.process.pid:
            rss = _proc_rss_mb(handle.process.pid)
            if rss is not None and rss > pol.max_rss_mb:
                return f"rss {rss:.0f}MB > {pol.max_rss_mb:.0f}MB"
        return None

    async def _watchdog_loop(self):
        while True:
            await asyncio.sleep(self.recycle.check_interval_s)
            try:
                await self._watchdog_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                # The watchdog must NEVER die silently: a single bad
                # tick (transient scrape error, racing delete) skips,
                # the next interval retries.
                logger.exception("recycle watchdog tick failed")

    async def _watchdog_tick(self):
            for cid, comp in list(self.state.items()):
                for replica in list(comp.replicas):
                    if id(replica) in self._recycling:
                        continue
                    handle: _Proc = replica.handle
                    if handle is None or \
                            handle.process.returncode is not None:
                        continue
                    age = asyncio.get_running_loop().time() \
                        - handle.spawned_at
                    if age < self.recycle.min_age_s:
                        continue  # successor grace: no thrash loop
                    reason = self._over_threshold(handle)
                    if reason is None and \
                            self.recycle.max_requests is not None:
                        n = await self._request_count(replica.host)
                        if n is not None and \
                                n >= self.recycle.max_requests:
                            reason = (f"served {n:.0f} >= "
                                      f"{self.recycle.max_requests} "
                                      "requests")
                    if reason is not None:
                        self._recycling.add(id(replica))
                        try:
                            await self._recycle_replica(replica, reason)
                        except Exception:
                            logger.exception(
                                "recycle of %s failed", replica.host)
                        finally:
                            self._recycling.discard(id(replica))

    async def _recycle_replica(self, replica: Replica, reason: str):
        """Drain-then-replace.  overlap: successor first (zero-gap; CPU
        replicas).  Chip owners (overlap=False): the old process must
        release the TPU before the successor can initialize — the
        router's buffering/failover carries requests across the gap."""
        logger.warning("recycling replica %s at %s: %s",
                       replica.component_id, replica.host, reason)
        handle: _Proc = replica.handle
        # Hold a create reservation across the WHOLE swap: in the
        # overlap=False drain window (SIGTERM grace, up to TERM_GRACE_S)
        # the replica is already out of state and the successor's create
        # hasn't started, so without this the reconciler/autoscaler sees
        # have < want and spawns its own replacement while the old
        # process still owns the chip.
        key = (replica.component_id, replica.revision)
        self._creating[key] = self._creating.get(key, 0) + 1
        try:
            if self.recycle.overlap:
                loop = asyncio.get_running_loop()
                t_spawn = loop.time()
                successor = await self.create_replica(
                    replica.component_id, replica.revision, handle.spec,
                    placement=replica.placement,
                    nice=self.recycle.successor_nice,
                    minimal_warmup=True)
                # Loaded and serving: restore normal CPU priority.
                if self.recycle.successor_nice > 0:
                    try:
                        os.setpriority(os.PRIO_PROCESS,
                                       successor.handle.process.pid, 0)
                    except (OSError, AttributeError) as e:
                        # Lowering nice needs CAP_SYS_NICE; without it
                        # the replica SERVES at nice 15 — loud warning,
                        # because host contention then starves it
                        # permanently, not just during the swap.
                        logger.warning(
                            "cannot renice successor %s back to 0 "
                            "(%s); it will serve at nice %d — grant "
                            "CAP_SYS_NICE or set RecyclePolicy."
                            "successor_nice=0",
                            successor.handle.process.pid, e,
                            self.recycle.successor_nice)
                t0 = loop.time()
                await self.delete_replica(replica)
                # Zero-gap swap: the successor was serving before the
                # old replica left rotation — no unavailability window.
                self.swap_windows_s.append(0.0)
                self.swap_breakdown.append({
                    "successor_load_s": round(t0 - t_spawn, 2),
                    "drain_s": round(loop.time() - t0, 2),
                    # Where the load time went, from the successor's
                    # own boot marks (interpreter_imports / download /
                    # init_params / warmup / serving, cumulative
                    # seconds since process birth).
                    "successor_phases": await self._startup_phases(
                        successor.host),
                })
            elif self._standby_capable(handle.spec):
                # Fast swap: spawn the successor in STANDBY while the
                # old process still serves and owns the chip —
                # interpreter start, jax/flax imports, artifact
                # download all happen outside the gap.  The gap is only
                # [old SIGTERM+exit] + [device init + cache-hot compile
                # + warmup], measured into swap_windows_s.
                loop = asyncio.get_running_loop()
                t_spawn = loop.time()
                standby = await self.create_replica(
                    replica.component_id, replica.revision, handle.spec,
                    placement=replica.placement, standby=True)
                activated = False
                try:
                    t0 = loop.time()
                    await self.delete_replica(replica)
                    t_drained = loop.time()
                    try:
                        await self._activate_standby(standby)
                        activated = True
                    except Exception:
                        # Successor unusable: fall back to a cold spawn
                        # so the component is not left at zero replicas.
                        logger.exception(
                            "standby activation failed; cold respawn")
                        await self.create_replica(
                            replica.component_id, replica.revision,
                            handle.spec, placement=replica.placement)
                finally:
                    # A standby successor lives OUTSIDE self.state until
                    # activation: any exit without activation (failure,
                    # shutdown cancelling this task) must reap it here
                    # or it orphans — on an exclusive-device pod an
                    # orphan holds the chip forever.
                    if not activated:
                        await asyncio.shield(
                            self._terminate(standby.handle.process))
                window = loop.time() - t0
                self.swap_windows_s.append(round(window, 3))
                self.swap_breakdown.append({
                    "standby_spawn_s": round(t0 - t_spawn, 2),
                    "drain_s": round(t_drained - t0, 2),
                    "activate_s": round(loop.time() - t_drained, 2),
                })
                self.standby_swaps += 1
                logger.info("recycle swap window: %.2fs (drain %.2fs "
                            "activate %.2fs)", window, t_drained - t0,
                            loop.time() - t_drained)
            else:
                loop = asyncio.get_running_loop()
                t0 = loop.time()
                await self.delete_replica(replica)
                await self.create_replica(
                    replica.component_id, replica.revision, handle.spec,
                    placement=replica.placement, minimal_warmup=True)
                self.swap_windows_s.append(
                    round(loop.time() - t0, 3))
        finally:
            n = self._creating.get(key, 1) - 1
            if n <= 0:
                self._creating.pop(key, None)
            else:
                self._creating[key] = n
        self.recycle_count += 1

    async def delete_replica(self, replica: Replica) -> None:
        comp = self.state.get(replica.component_id)
        if comp and replica in comp.replicas:
            comp.replicas.remove(replica)
        handle: _Proc = replica.handle
        if handle is not None:
            await self._terminate(handle.process)
        logger.info("replica down: %s at %s",
                    replica.component_id, replica.host)

    @staticmethod
    async def _terminate(process) -> None:
        if process.returncode is not None:
            return
        process.terminate()
        try:
            await asyncio.wait_for(process.wait(), TERM_GRACE_S)
        except asyncio.TimeoutError:
            process.kill()
            await process.wait()

    async def shutdown(self):
        if self._watchdog is not None:
            self._watchdog.cancel()
            try:
                await self._watchdog
            except (asyncio.CancelledError, Exception):
                pass
            self._watchdog = None
        for comp in list(self.state.values()):
            for replica in list(comp.replicas):
                await self.delete_replica(replica)
