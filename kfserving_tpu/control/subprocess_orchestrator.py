"""SubprocessOrchestrator: replicas are real OS processes.

The reference's replicas are pods created by Knative from the ksvc the
reconciler writes (reference ksvc_reconciler.go:153-187); the
single-host TPU equivalent is one process per replica, exec'd from the
per-framework entrypoint module registered in the cluster config
(`python -m kfserving_tpu.predictors.<fw> --model_name ... --model_dir
... --http_port ...` — the same arg convention the reference's
predictor specs build, predictor_sklearn.go:77-96).

Readiness mirrors the pod readiness probe: the replica joins the
router's rotation only after its health route answers.  Deletion is
SIGTERM (the server's signal handler drains in-flight work) escalating
to SIGKILL.

TPU note: on a single chip only one process can own the device; either
give each JAX replica a distinct mesh slice via env (TPU_VISIBLE_DEVICES
/ JAX_PLATFORMS) through `env_overrides`, or keep max_replicas=1 for
chip-owning predictors.  CPU frameworks (sklearn/xgb/...) scale freely.
"""

import asyncio
import logging
import os
import socket
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kfserving_tpu.control.clusterconfig import ClusterConfig
from kfserving_tpu.control.orchestrator import Replica, _ComponentState
from kfserving_tpu.observability import metrics as obs

logger = logging.getLogger("kfserving_tpu.control.subprocess")

READY_TIMEOUT_S = 120.0
TERM_GRACE_S = 10.0


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@dataclass
class RecyclePolicy:
    """Replica process recycling (ROOFLINE.md soak: the tunneled device
    transport leaks ~3.2 GB/min under load; the pod-level analogue is
    kubelet restarting a container that crosses its memory limit —
    SURVEY.md §5.3 delegation, built natively here).

    A replica crossing either threshold is drain-replaced; the old
    process gets SIGTERM (the server's handler drains in-flight
    work).  The router's readiness gating, scale-from-zero buffering,
    and announced-swap holds carry traffic across the swap.

    Standby-capable replicas (jax/generative, KFS_STANDBY honored)
    ALWAYS recycle through the warm-standby lifecycle — TensorFlow-
    Serving's aspired-versions discipline (arxiv 1712.06139): the
    successor loads FULLY warm (standby spawn -> /standby/activate,
    params mapped from the mmap cache, compile-cache-hot warmup)
    while the incumbent still serves, and only then does the incumbent
    drain.  An activation failure keeps the incumbent serving and
    tears the broken standby down (counted in
    kfserving_tpu_lifecycle_swap_failures_total) — a swap can only
    make things better.  The same armed standbys back crash
    promotion: a replica that dies (process exit, or
    health_fail_threshold consecutive probe failures, or a router
    crash report) is replaced by activating its standby within one
    supervisor tick.

    exclusive_device=True is for deployments where the transport
    admits ONE resident process (real TPU pods, where libtpu locks
    the chip): there the standby cannot touch the device until the
    incumbent exits, so the order is drain -> activate and the
    orchestrator ANNOUNCES the swap window (swap_announced) so the
    router holds requests in a bounded queue instead of shedding
    503s across it.

    Standby-incapable frameworks (sklearn/xgb/custom) keep the older
    paths: overlap=True (default) fully loads a successor before the
    drain; overlap=False is the cold drain-then-respawn.
    """

    max_requests: Optional[int] = None
    max_rss_mb: Optional[float] = None
    check_interval_s: float = 5.0
    overlap: bool = True
    # Exclusive-device transport: standby activation must wait for the
    # incumbent's exit (drain -> activate, announced swap window).
    exclusive_device: bool = False
    # Keep one armed standby (spawned, imports + artifact done, device
    # untouched) per component: recycles skip the spawn phase and
    # crash promotion has a warm successor ready.
    standby_pool: bool = True
    # Crash supervision: dead processes (and replicas failing this
    # many consecutive health probes — 0 disables probing) are
    # replaced by standby promotion in the same watchdog tick.
    crash_supervision: bool = True
    health_fail_threshold: int = 3
    # Router hold budget announced for an exclusive-device swap (the
    # drain -> activate gap it must bridge).
    announce_budget_s: float = 30.0
    # Successor grace: a replica younger than this is never recycled.
    # Without it, a threshold at/below a fresh process's baseline RSS
    # (easy with JAX loaded) would kill/spawn in an unbounded loop with
    # a zero-replica gap per cycle on chip owners.
    min_age_s: float = 30.0
    # Overlapped successors load at this nice level and are restored to
    # 0 once serving.  On a small host the successor's XLA
    # compile/deserialize otherwise starves the OLD replica's event
    # loop for the whole load — measured soak p99 went 0.7s -> 27s from
    # CPU contention alone, with zero unavailability.
    successor_nice: int = 15


def _proc_rss_mb(pid: int) -> Optional[float]:
    """Resident set size of a pid in MB (Linux /proc, no psutil)."""
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        return None
    return None


@dataclass
class _Proc:
    process: asyncio.subprocess.Process
    port: int
    spec: object = None
    spawned_at: float = 0.0


class SubprocessOrchestrator:
    """Actuation backend that execs one server process per replica."""

    def __init__(self, cluster_config: Optional[ClusterConfig] = None,
                 env_overrides: Optional[Dict[str, str]] = None,
                 host: str = "127.0.0.1",
                 credentials=None,
                 recycle: Optional[RecyclePolicy] = None):
        self.cluster_config = cluster_config or ClusterConfig()
        self.env_overrides = env_overrides or {}
        self.host = host
        # CredentialStore: per-service-account env injected into replica
        # processes (reference credential builder injects into containers).
        self.credentials = credentials
        self.recycle = recycle
        self.recycle_count = 0
        # Chip-release -> successor-serving gap of each swap (the
        # soak's swap_window_s stat; warm-standby swaps record 0.0 —
        # the successor was serving before the incumbent left).
        self.swap_windows_s: List[float] = []
        self.standby_swaps = 0
        self.swap_failures = 0
        self.promotions = 0
        # Per-swap phase timing: {"mode", "standby_spawn_s",
        # "activate_s", "drain_s", ...} — which part to attack next.
        self.swap_breakdown: List[Dict[str, float]] = []
        # Announced swap windows: component_id -> loop-time deadline.
        # The router holds (bounded queue, never 503) requests for a
        # component inside its announced drain->activate window.
        self.swap_announced: Dict[str, float] = {}
        # Armed standbys ((cid, revision) -> [Replica, ...]): spawned
        # with KFS_STANDBY (imports + artifact done, device
        # untouched), promoted on recycle or crash — and, since the
        # predictive control loop (ISSUE 12), adopted directly by
        # scale-ups (reconciler._scale_revisions prefers an armed
        # standby over a cold spawn).  Pool depth per component is
        # self._standby_targets (default 1); the feed-forward
        # autoscaler pre-arms the pool to its predicted capacity gap
        # so the actuation cost of a traffic step is one activation,
        # not a cold spawn.
        self._standbys: Dict[tuple, List[Replica]] = {}
        self._standby_spawning: Dict[tuple, int] = {}
        self._standby_targets: Dict[str, int] = {}
        self._health_fails: Dict[int, int] = {}
        # Supervisor flight recorder: failover and swap-failure
        # timelines pinned in the control-plane process (the router
        # federates it under replica="supervisor").
        from kfserving_tpu.observability.monitoring import (
            FlightRecorder,
        )

        self.flight_recorder = FlightRecorder.from_env()
        self._watchdog: Optional[asyncio.Task] = None
        self._recycling: set = set()  # replica ids being swapped
        # (cid, revision) -> count of creates past spawn but not yet
        # ready.  replicas() lists only ready processes, so without this
        # the reconciler's scale-up and the recycler would both spawn
        # during a swap window — fatal for chip-owning replicas (one
        # process per TPU).
        self._creating: Dict[tuple, int] = {}
        self.state: Dict[str, _ComponentState] = {}
        # Cluster-local gateway address, published by the ingress router
        # at start (router.py start_async); replicas get it as
        # KFS_CLUSTER_LOCAL_URL.
        self.cluster_local_url: Optional[str] = None

    def pending_creates(self, component_id: str, revision: str) -> int:
        return self._creating.get((component_id, revision), 0)

    def replicas(self, component_id: str) -> List[Replica]:
        return list(self.state.get(component_id,
                                   _ComponentState()).replicas)

    # -- spec -> argv -------------------------------------------------------
    def _command(self, component_id: str, spec, port: int) -> List[str]:
        from kfserving_tpu.control.spec import (
            ExplainerSpec,
            PredictorSpec,
            TransformerSpec,
        )

        isvc_name = component_id.split("/")[1]
        if isinstance(spec, (TransformerSpec, ExplainerSpec)) and \
                getattr(spec, "command", None):
            return list(spec.command) + ["--http_port", str(port)]
        if isinstance(spec, ExplainerSpec):
            # In-tree explainer types run via the standalone explainer
            # server (the reference's per-explainer binaries,
            # alibiexplainer/__main__.py); predictor_host arrives via
            # the injected KFS_CLUSTER_LOCAL_URL.  Unknown types must
            # fail HERE with a clear error — the child's stderr goes to
            # DEVNULL, so an argparse rejection would surface only as
            # an opaque readiness failure.
            from kfserving_tpu.explainers import (
                ARTIFACT_REQUIRED_TYPES,
                EXPLAINER_TYPES,
            )

            if spec.explainer_type not in EXPLAINER_TYPES:
                raise ValueError(
                    f"explainer_type {spec.explainer_type!r} needs an "
                    f"explicit command under the subprocess "
                    f"orchestrator (in-tree: {list(EXPLAINER_TYPES)})")
            if spec.explainer_type in ARTIFACT_REQUIRED_TYPES and \
                    not spec.storage_uri:
                # Without the artifact dir the child dies in
                # Storage.download with stderr discarded.
                raise ValueError(
                    f"{spec.explainer_type} explainer needs a "
                    f"storage_uri")
            argv = [sys.executable, "-m", "kfserving_tpu.explainers",
                    "--model_name", isvc_name,
                    "--explainer_type", spec.explainer_type,
                    "--http_port", str(port)]
            if spec.storage_uri:
                argv += ["--storage_uri", spec.storage_uri]
            if spec.container_concurrency:
                argv += ["--container_concurrency",
                         str(spec.container_concurrency)]
            return argv
        if isinstance(spec, PredictorSpec):
            if spec.framework == "custom":
                if not spec.command:
                    raise ValueError(
                        "custom predictor needs an explicit command")
                return list(spec.command) + ["--http_port", str(port)]
            from kfserving_tpu.control.spec import (
                EXTERNAL_RUNTIME_FRAMEWORKS,
            )

            if spec.framework in EXTERNAL_RUNTIME_FRAMEWORKS:
                return self._external_command(component_id, spec, port)
            runtime = self.cluster_config.runtime_for(spec.framework)
            argv = [sys.executable, "-m", runtime["module"],
                    "--model_name", isvc_name,
                    "--model_dir", spec.storage_uri,
                    "--http_port", str(port)]
            if spec.container_concurrency:
                argv += ["--container_concurrency",
                         str(spec.container_concurrency)]
            if spec.batcher is not None:
                argv += ["--max_batch_size",
                         str(spec.batcher.max_batch_size),
                         "--max_latency_ms",
                         str(spec.batcher.max_latency_ms)]
            if spec.multi_model:
                argv += ["--multi_model"]
            return argv
        raise ValueError(
            f"subprocess orchestrator cannot run component spec "
            f"{type(spec).__name__} without an explicit command")

    def _external_command(self, component_id: str, spec,
                          port: int) -> List[str]:
        """argv for an external server runtime, per that runtime's own
        CLI convention — the reference builds the same argument lists
        into its container specs (predictor_tfserving.go:84-90,
        predictor_triton.go:59-67, predictor_onnxruntime.go:67-72).
        The binary comes from the cluster config's `command` entry
        (spec.command overrides it, e.g. a site wrapper script)."""
        isvc_name = component_id.split("/")[1]
        runtime = self.cluster_config.runtime_for(spec.framework)
        base = list(spec.command or runtime.get("command") or ())
        if not base:
            raise ValueError(
                f"framework {spec.framework!r} needs a configured "
                f"external server command (cluster config predictors."
                f"{spec.framework}.command)")
        if not spec.storage_uri:
            raise ValueError(
                f"{spec.framework} predictor needs a storage_uri")
        model_dir = spec.storage_uri
        for prefix in ("file://",):
            if model_dir.startswith(prefix):
                model_dir = model_dir[len(prefix):]
        style = runtime.get("argStyle", spec.framework)
        if style == "tfserving":
            return base + [
                f"--rest_api_port={port}",
                f"--model_name={isvc_name}",
                f"--model_base_path={model_dir}",
            ]
        if style == "triton":
            return base + [
                f"--model-store={model_dir}",
                f"--http-port={port}",
                "--allow-http=true",
            ]
        if style == "onnx":
            return base + [
                f"--model_path={model_dir}",
                f"--http_port={port}",
            ]
        raise ValueError(f"unknown external argStyle {style!r}")

    # -- lifecycle ----------------------------------------------------------
    def _standby_capable(self, spec) -> bool:
        """Standby fast-swap needs the runtime to honor KFS_STANDBY
        (deferred device-touching load behind POST /standby/activate) —
        the chip-owning in-tree servers do."""
        from kfserving_tpu.control.spec import PredictorSpec

        return (isinstance(spec, PredictorSpec)
                and spec.framework in ("jax", "generative")
                and not getattr(spec, "multi_model", False))

    async def create_replica(self, component_id: str, revision: str,
                             spec, placement=None,
                             standby: bool = False,
                             nice: int = 0,
                             minimal_warmup: bool = False) -> Replica:
        port = _free_port(self.host)
        argv = self._command(component_id, spec, port)
        env = dict(os.environ)
        if standby:
            env["KFS_STANDBY"] = "1"
        if minimal_warmup or standby:
            # Recycle successors (and standby activations, whose
            # warmup sits inside the exclusive-device swap gap) warm
            # only the largest bucket: the predecessor populated the
            # persistent compile cache, so the rest load on demand —
            # the full grid was the dominant term of successor load
            # time (r5 SOAK successor_phases).
            env["KFS_MINIMAL_WARMUP"] = "1"
        else:
            # A cold first replica (empty persistent cache) must do
            # the full grid; never inherit a stray flag from the
            # orchestrator's own environment.
            env.pop("KFS_MINIMAL_WARMUP", None)
        # The package must be importable from the child even when not
        # pip-installed.
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (
            repo_root + os.pathsep + env.get("PYTHONPATH", "")).rstrip(
                os.pathsep)
        if self.credentials is not None:
            env.update(self.credentials.build_env(
                getattr(spec, "service_account_name", "default")))
        if placement is not None:
            # Slice discovery env — the TPU analogue of the reference's
            # injected nodeSelector (accelerator_injector.go:38-44).
            env.update(placement.env())
        if self.cluster_local_url:
            # Custom explainer/transformer commands reach the predictor
            # through the gateway's direct lane (the reference injects
            # --predictor_host into those containers).
            env["KFS_CLUSTER_LOCAL_URL"] = self.cluster_local_url
        env.update(self.env_overrides)
        logger.info("spawning replica %s rev=%s%s: %s",
                    component_id, revision[:8],
                    " (standby)" if standby else "", " ".join(argv))
        key = (component_id, revision)
        # Standby spawns do NOT reserve a create: they are not serving
        # capacity (the reconciler must still scale the component up
        # while a pool standby arms).  The swap/promotion paths that
        # consume a standby hold their own reservation.
        if not standby:
            self._creating[key] = self._creating.get(key, 0) + 1
        try:
            preexec = None
            if nice > 0:
                def preexec(n=nice):  # runs in the child pre-exec
                    os.nice(n)
            process = await asyncio.create_subprocess_exec(
                *argv, env=env,
                stdout=asyncio.subprocess.DEVNULL,
                stderr=asyncio.subprocess.DEVNULL,
                preexec_fn=preexec)
            host = f"{self.host}:{port}"
            try:
                await self._wait_ready(process, host)
            except Exception:
                await self._terminate(process)
                raise
        finally:
            if not standby:
                n = self._creating.get(key, 1) - 1
                if n <= 0:
                    self._creating.pop(key, None)
                else:
                    self._creating[key] = n
        replica = Replica(component_id, revision, host,
                          handle=_Proc(
                              process, port, spec=spec,
                              spawned_at=asyncio.get_running_loop().time()),
                          placement=placement)
        if standby:
            # Not serving yet: joins `state` (and the router's
            # rotation) only after _activate_standby succeeds.
            return replica
        self.state.setdefault(component_id,
                              _ComponentState()).replicas.append(replica)
        if self.recycle is not None and self._watchdog is None:
            self._watchdog = asyncio.ensure_future(self._watchdog_loop())
        return replica

    # -- announced swap windows --------------------------------------------
    def announce_swap(self, component_id: str, expected_s: float) -> None:
        """Publish a drain->activate window: the router holds (bounded
        queue) requests for this component until the window closes or a
        replica reappears, instead of shedding 503s across the swap."""
        self.swap_announced[component_id] = \
            asyncio.get_running_loop().time() + expected_s

    def clear_swap(self, component_id: str) -> None:
        self.swap_announced.pop(component_id, None)

    async def _activate_standby(self, replica: Replica) -> None:
        """Flip a standby successor live: POST its activation route (the
        deferred device-touching load runs there), then enter it into
        the serving state."""
        import aiohttp

        from kfserving_tpu.reliability import fault_sites, faults

        # Chaos hook: an injected error/hang here drives the
        # activation-failure path (incumbent kept, standby reaped)
        # without breaking a real process.
        await faults.inject(
            fault_sites.ORCHESTRATOR_STANDBY_ACTIVATE,
            key=f"{replica.host} {replica.component_id} "
                f"revision:{replica.revision}")
        url = f"http://{replica.host}/standby/activate"
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=READY_TIMEOUT_S)) as session:
            async with session.post(url) as resp:
                body = await resp.text()
                if resp.status != 200:
                    raise RuntimeError(
                        f"standby activation at {replica.host} failed "
                        f"({resp.status}): {body[:500]}")
        # The min_age_s successor grace measures time SERVING, not time
        # armed: a standby that sat in the pool for minutes must not be
        # instantly re-recycled by a threshold at/below its baseline
        # (the thrash loop min_age_s exists to prevent).
        if replica.handle is not None:
            replica.handle.spawned_at = \
                asyncio.get_running_loop().time()
        self.state.setdefault(replica.component_id,
                              _ComponentState()).replicas.append(replica)
        if self.recycle is not None and self._watchdog is None:
            self._watchdog = asyncio.ensure_future(self._watchdog_loop())

    async def _wait_ready(self, process, host: str) -> None:
        """Poll the liveness route until it answers (readiness probe)."""
        import aiohttp

        deadline = asyncio.get_running_loop().time() + READY_TIMEOUT_S
        url = f"http://{host}/"
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=2.0)) as session:
            while True:
                if process.returncode is not None:
                    raise RuntimeError(
                        f"replica process exited rc={process.returncode} "
                        f"before becoming ready")
                try:
                    async with session.get(url) as resp:
                        if resp.status == 200:
                            return
                except Exception:
                    pass
                if asyncio.get_running_loop().time() > deadline:
                    raise TimeoutError(
                        f"replica at {host} not ready after "
                        f"{READY_TIMEOUT_S}s")
                await asyncio.sleep(0.1)

    # -- recycling ----------------------------------------------------------
    async def _startup_phases(self, host: str) -> Dict[str, float]:
        import aiohttp

        try:
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=2.0)) as session:
                async with session.get(
                        f"http://{host}/startup_phases") as resp:
                    if resp.status == 200:
                        return await resp.json()
        except Exception:
            logger.debug("startup phases scrape of %s failed", host)
        return {}

    async def _request_count(self, host: str) -> Optional[float]:
        """Best-effort scrape of the replica's request counter (the
        server's Prometheus text endpoint)."""
        import aiohttp

        try:
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=2.0)) as session:
                async with session.get(f"http://{host}/metrics") as resp:
                    if resp.status != 200:
                        return None
                    text = await resp.text()
        except Exception:
            return None
        from kfserving_tpu.server.metrics import REQUEST_TOTAL_SERIES

        total = 0.0
        for line in text.splitlines():
            if line.startswith(REQUEST_TOTAL_SERIES + "{"):
                try:
                    total += float(line.rsplit(" ", 1)[1])
                except (IndexError, ValueError):
                    pass
        return total

    def _over_threshold(self, handle: _Proc) -> Optional[str]:
        pol = self.recycle
        if pol.max_rss_mb is not None and handle.process.pid:
            rss = _proc_rss_mb(handle.process.pid)
            if rss is not None and rss > pol.max_rss_mb:
                return f"rss {rss:.0f}MB > {pol.max_rss_mb:.0f}MB"
        return None

    async def _watchdog_loop(self):
        while True:
            await asyncio.sleep(self.recycle.check_interval_s)
            try:
                await self._watchdog_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                # The watchdog must NEVER die silently: a single bad
                # tick (transient scrape error, racing delete) skips,
                # the next interval retries.
                logger.exception("recycle watchdog tick failed")

    async def _watchdog_tick(self):
            # Crash supervision FIRST: a dead replica's standby is
            # promoted in this same tick, before pool maintenance or
            # threshold recycling reason about capacity.
            if self.recycle.crash_supervision:
                await self._supervise_crashes()
            for cid, comp in list(self.state.items()):
                for replica in list(comp.replicas):
                    if id(replica) in self._recycling:
                        continue
                    handle: _Proc = replica.handle
                    if handle is None or \
                            handle.process.returncode is not None:
                        continue
                    age = asyncio.get_running_loop().time() \
                        - handle.spawned_at
                    if age < self.recycle.min_age_s:
                        continue  # successor grace: no thrash loop
                    # kfslint: disable=async-blocking — /proc reads
                    # are RAM-backed (never disk), microseconds per
                    # replica.
                    reason = self._over_threshold(handle)
                    if reason is None and \
                            self.recycle.max_requests is not None:
                        n = await self._request_count(replica.host)
                        if n is not None and \
                                n >= self.recycle.max_requests:
                            reason = (f"served {n:.0f} >= "
                                      f"{self.recycle.max_requests} "
                                      "requests")
                    if reason is not None:
                        self._recycling.add(id(replica))
                        try:
                            await self._recycle_replica(replica, reason)
                        except Exception:
                            logger.exception(
                                "recycle of %s failed", replica.host)
                        finally:
                            self._recycling.discard(id(replica))
            self._reap_orphan_standbys()
            if self.recycle.standby_pool:
                self._maintain_standby_pool()

    async def _recycle_replica(self, replica: Replica, reason: str):
        """Drain-then-replace, by lifecycle mode.  Standby-capable
        replicas take the warm-standby path (activate BEFORE drain —
        or after, announced, on exclusive-device transports); CPU
        frameworks keep the overlapped/cold successor paths."""
        logger.warning("recycling replica %s at %s: %s",
                       replica.component_id, replica.host, reason)
        handle: _Proc = replica.handle
        # Hold a create reservation across the WHOLE swap: in any
        # drain window (SIGTERM grace, up to TERM_GRACE_S) the replica
        # is already out of state and the successor not yet entered,
        # so without this the reconciler/autoscaler sees have < want
        # and spawns its own replacement while the old process still
        # owns the chip.
        key = (replica.component_id, replica.revision)
        self._creating[key] = self._creating.get(key, 0) + 1
        try:
            if self._standby_capable(handle.spec):
                if self.recycle.exclusive_device:
                    ok = await self._exclusive_standby_swap(replica)
                else:
                    ok = await self._warm_standby_swap(replica)
                if not ok:
                    return  # incumbent kept serving; not a recycle
            elif self.recycle.overlap:
                await self._overlap_swap(replica)
            else:
                await self._cold_swap(replica)
        finally:
            n = self._creating.get(key, 1) - 1
            if n <= 0:
                self._creating.pop(key, None)
            else:
                self._creating[key] = n
        self.recycle_count += 1

    def _pop_standby(self, key: tuple) -> Optional[Replica]:
        """Pop one LIVE armed standby for (cid, revision); pool
        corpses are discarded on the way (the next maintenance tick
        re-arms)."""
        pool = self._standbys.get(key)
        popped = None
        while pool:
            candidate = pool.pop(0)
            if candidate.handle.process.returncode is None:
                popped = candidate
                break
            logger.warning("pooled standby for %s died (rc=%s); "
                           "discarded", key[0],
                           candidate.handle.process.returncode)
        if not pool:
            self._standbys.pop(key, None)
        self._set_pool_gauge(key[0])
        return popped

    # -- predictive pre-arming (control/predictive.py) ----------------------
    def set_standby_target(self, component_id: str, target: int) -> None:
        """Size the armed-standby pool for a component: the feed-
        forward autoscaler pre-arms `target` standbys ahead of a
        predicted capacity gap so scale-up actuates as one-tick
        activations.  1 is the lifecycle default (crash failover
        always wants a warm successor); the cap keeps a runaway
        prediction from forking the host to death."""
        target = max(1, min(int(target), 8))
        if self._standby_targets.get(component_id, 1) != target:
            logger.info("standby pool target for %s -> %d",
                        component_id, target)
        self._standby_targets[component_id] = target

    def standby_target(self, component_id: str) -> int:
        return self._standby_targets.get(component_id, 1)

    def standby_count(self, component_id: str) -> int:
        """Live armed standbys for a component (the capacity the
        predictive loop can actuate without a spawn)."""
        return sum(
            1 for (cid, _rev), pool in self._standbys.items()
            if cid == component_id
            for r in pool if r.handle.process.returncode is None)

    async def adopt_standby(self, component_id: str,
                            revision: str) -> Optional[Replica]:
        """Scale-up fast path: activate an armed standby into serving
        instead of cold-spawning.  Returns the serving replica, or
        None when no live standby exists (or activation failed — the
        caller falls back to create_replica)."""
        standby = self._pop_standby((component_id, revision))
        if standby is None:
            return None
        key = (component_id, revision)
        # Reservation across the activation: replicas() lists only
        # serving processes, so without it a concurrent reconcile
        # would double-spawn while this standby activates.
        self._creating[key] = self._creating.get(key, 0) + 1
        try:
            await asyncio.wait_for(self._activate_standby(standby),
                                   READY_TIMEOUT_S)
        except asyncio.CancelledError:
            await asyncio.shield(
                self._terminate(standby.handle.process))
            raise
        except Exception:
            logger.exception("standby adoption for %s failed; caller "
                             "falls back to cold spawn", component_id)
            await asyncio.shield(
                self._terminate(standby.handle.process))
            return None
        finally:
            n = self._creating.get(key, 1) - 1
            if n <= 0:
                self._creating.pop(key, None)
            else:
                self._creating[key] = n
        obs.lifecycle_promotions_total().labels(
            trigger="scale_up", outcome="promoted").inc()
        logger.info("scale-up adopted armed standby %s for %s",
                    standby.host, component_id)
        return standby

    async def _obtain_standby(self, cid: str, revision: str, spec,
                              placement) -> Tuple[Replica, float]:
        """An armed standby for (cid, revision): a pooled one when it
        is still alive (spawn cost already paid outside the swap), else
        a fresh spawn.  Returns (standby, spawn_seconds)."""
        loop = asyncio.get_running_loop()
        pooled = self._pop_standby((cid, revision))
        if pooled is not None:
            return pooled, 0.0
        t0 = loop.time()
        standby = await self.create_replica(cid, revision, spec,
                                            placement=placement,
                                            standby=True)
        return standby, loop.time() - t0

    async def _warm_standby_swap(self, replica: Replica) -> bool:
        """The default lifecycle (TF-Serving aspired-versions order):
        the successor activates — device load off the mmap param
        cache, cache-hot warmup — while the incumbent still serves,
        and the incumbent drains only once the successor is IN the
        rotation.  Swap window: 0 by construction.  Returns False when
        activation failed (incumbent kept serving)."""
        loop = asyncio.get_running_loop()
        cid, rev = replica.component_id, replica.revision
        standby, spawn_s = await self._obtain_standby(
            cid, rev, replica.handle.spec, replica.placement)
        t0 = loop.time()
        try:
            await asyncio.wait_for(self._activate_standby(standby),
                                   READY_TIMEOUT_S)
        except asyncio.CancelledError:
            # Shutdown cancelling the watchdog mid-activate: the
            # standby is outside self.state and already popped from
            # the pool — reap it here or it orphans as a live process.
            await asyncio.shield(
                self._terminate(standby.handle.process))
            raise
        except Exception as e:
            await asyncio.shield(
                self._terminate(standby.handle.process))
            self._swap_failed(replica, standby, e,
                              mode="warm_standby")
            return False
        activate_s = loop.time() - t0
        t1 = loop.time()
        await self.delete_replica(replica)
        drain_s = loop.time() - t1
        # Incumbent gone (drain exported its live KV, exit released
        # its manifest flock): the successor adopts the generation so
        # returning conversations fault back instead of re-prefilling.
        await self._kv_reattach(standby.host)
        # The successor was serving before the incumbent left
        # rotation — no unavailability window.
        self.swap_windows_s.append(0.0)
        self.swap_breakdown.append({
            "mode": "warm_standby",
            "standby_spawn_s": round(spawn_s, 2),
            "activate_s": round(activate_s, 2),
            "drain_s": round(drain_s, 2),
            "successor_phases": await self._startup_phases(
                standby.host),
        })
        self.standby_swaps += 1
        self._observe_swap("warm_standby", "ok",
                           standby_spawn=spawn_s,
                           activate=activate_s, drain=drain_s)
        logger.info("warm standby swap of %s: activate %.2fs "
                    "(spawn %.2fs) drain %.2fs, window 0", cid,
                    activate_s, spawn_s, drain_s)
        return True

    async def _exclusive_standby_swap(self, replica: Replica) -> bool:
        """Exclusive-device order: the incumbent must release the chip
        before the standby can touch it — drain, then activate, inside
        an ANNOUNCED window the router bridges by holding requests."""
        loop = asyncio.get_running_loop()
        cid, rev = replica.component_id, replica.revision
        standby, spawn_s = await self._obtain_standby(
            cid, rev, replica.handle.spec, replica.placement)
        activated = False
        self.announce_swap(cid, self.recycle.announce_budget_s)
        try:
            t0 = loop.time()
            await self.delete_replica(replica)
            t_drained = loop.time()
            try:
                await asyncio.wait_for(
                    self._activate_standby(standby), READY_TIMEOUT_S)
                activated = True
            except Exception as e:
                # Successor unusable AND the incumbent is already
                # gone: cold respawn so the component is not left at
                # zero replicas.
                self._swap_failed(replica, standby, e,
                                  mode="exclusive_standby")
                logger.exception(
                    "standby activation failed; cold respawn")
                await self.create_replica(
                    cid, rev, replica.handle.spec,
                    placement=replica.placement)
        finally:
            self.clear_swap(cid)
            # A standby successor lives OUTSIDE self.state until
            # activation: any exit without activation (failure,
            # shutdown cancelling this task) must reap it here or it
            # orphans — on an exclusive-device pod an orphan holds
            # the chip forever.
            if not activated:
                await asyncio.shield(
                    self._terminate(standby.handle.process))
        window = loop.time() - t0
        if activated:
            # Outside the announced window (it just cleared): adopt
            # the drained incumbent's KV generation best-effort.
            await self._kv_reattach(standby.host)
        self.swap_windows_s.append(round(window, 3))
        self.swap_breakdown.append({
            "mode": "exclusive_standby",
            "standby_spawn_s": round(spawn_s, 2),
            "drain_s": round(t_drained - t0, 2),
            "activate_s": round(loop.time() - t_drained, 2),
        })
        self.standby_swaps += 1
        if activated:
            # The failure branch was already counted by _swap_failed.
            self._observe_swap("exclusive_standby", "ok",
                               standby_spawn=spawn_s,
                               drain=t_drained - t0,
                               activate=loop.time() - t_drained)
        logger.info("recycle swap window: %.2fs (drain %.2fs "
                    "activate %.2fs)", window, t_drained - t0,
                    loop.time() - t_drained)
        return True

    async def _overlap_swap(self, replica: Replica) -> None:
        """Zero-gap overlapped successor for standby-incapable
        frameworks: full load aside, then rotate."""
        loop = asyncio.get_running_loop()
        t_spawn = loop.time()
        successor = await self.create_replica(
            replica.component_id, replica.revision,
            replica.handle.spec, placement=replica.placement,
            nice=self.recycle.successor_nice, minimal_warmup=True)
        # Loaded and serving: restore normal CPU priority.
        if self.recycle.successor_nice > 0:
            try:
                os.setpriority(os.PRIO_PROCESS,
                               successor.handle.process.pid, 0)
            except (OSError, AttributeError) as e:
                # Lowering nice needs CAP_SYS_NICE; without it the
                # replica SERVES at nice 15 — loud warning, because
                # host contention then starves it permanently, not
                # just during the swap.
                logger.warning(
                    "cannot renice successor %s back to 0 (%s); it "
                    "will serve at nice %d — grant CAP_SYS_NICE or "
                    "set RecyclePolicy.successor_nice=0",
                    successor.handle.process.pid, e,
                    self.recycle.successor_nice)
        t0 = loop.time()
        await self.delete_replica(replica)
        # Zero-gap swap: the successor was serving before the old
        # replica left rotation — no unavailability window.
        self.swap_windows_s.append(0.0)
        self.swap_breakdown.append({
            "mode": "overlap",
            "successor_load_s": round(t0 - t_spawn, 2),
            "drain_s": round(loop.time() - t0, 2),
            # Where the load time went, from the successor's own boot
            # marks (interpreter_imports / download / init_params or
            # params_mmap / warmup / serving, cumulative seconds
            # since process birth).
            "successor_phases": await self._startup_phases(
                successor.host),
        })
        self._observe_swap("overlap", "ok", drain=loop.time() - t0)

    async def _cold_swap(self, replica: Replica) -> None:
        loop = asyncio.get_running_loop()
        cid = replica.component_id
        self.announce_swap(cid, self.recycle.announce_budget_s)
        try:
            t0 = loop.time()
            await self.delete_replica(replica)
            await self.create_replica(
                cid, replica.revision, replica.handle.spec,
                placement=replica.placement, minimal_warmup=True)
        finally:
            self.clear_swap(cid)
        self.swap_windows_s.append(round(loop.time() - t0, 3))
        self.swap_breakdown.append({
            "mode": "cold",
            "window_s": round(loop.time() - t0, 2)})
        self._observe_swap("cold", "ok")

    def _swap_failed(self, replica: Replica, standby: Replica,
                     exc: Exception, mode: str) -> None:
        """Bookkeeping for an aborted standby swap: counted, pinned,
        and (warm mode) the incumbent keeps serving untouched."""
        reason = ("activate_timeout"
                  if isinstance(exc, asyncio.TimeoutError)
                  else "activate_error")
        self.swap_failures += 1
        obs.lifecycle_swap_failures_total().labels(
            reason=reason).inc()
        self._observe_swap(mode, "failed")
        self.flight_recorder.record({
            "kind": "swap_failure",
            "component": replica.component_id,
            "revision": replica.revision,
            "mode": mode, "reason": reason,
            "standby_host": standby.host,
            "incumbent_host": replica.host,
            "error": str(exc)[:500],
        }, pin="swap_failure")
        logger.error("standby swap of %s aborted (%s): %s%s",
                     replica.component_id, reason, exc,
                     f" — incumbent {replica.host} keeps serving"
                     if mode == "warm_standby" else "")

    @staticmethod
    def _observe_swap(mode: str, outcome: str, **phases_s) -> None:
        obs.lifecycle_swaps_total().labels(
            mode=mode, outcome=outcome).inc()
        hist = obs.lifecycle_phase_ms()
        for phase, seconds in phases_s.items():
            hist.labels(phase=phase).observe(seconds * 1000.0)

    # -- crash supervision & standby pool -----------------------------------
    async def _probe_health(self, host: str) -> bool:
        """Liveness probe with the router's `_replica_alive` polarity:
        only a refused/unroutable connection counts as a failure.  A
        TIMEOUT is indeterminate — a replica chewing a multi-second
        batch on its event loop can't answer, and promoting (killing)
        a busy replica would abort its in-flight inference — so it
        classifies as alive.  Health-fail promotion therefore targets
        the crashed-but-not-reaped shape: a process whose socket
        refuses while the pid lingers."""
        import aiohttp

        try:
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=2.0)) as s:
                async with s.get(f"http://{host}/") as resp:
                    return resp.status < 500
        except (aiohttp.ClientConnectorError, ConnectionRefusedError,
                OSError):
            return False
        except Exception:
            return True

    async def _kv_reattach(self, host: str) -> None:
        """Best-effort: tell a just-promoted successor to rescan the
        durable KV tier directory for its predecessor's generation.
        The predecessor's manifest flock releases on ANY process death
        (SIGKILL included), so by the time the successor is in
        rotation the adoption can take the orphaned manifest.  Runs
        AFTER the swap window clears — adoption must never extend
        unavailability, it only warms the fault-back path.  Failure is
        non-fatal: without a persistent tier the replica answers with
        an empty adoption, and a dead endpoint just means the session
        re-prefills."""
        import aiohttp

        try:
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=5.0)) as s:
                async with s.post(f"http://{host}/kv/reattach",
                                  json={}) as resp:
                    body = await resp.read()
                    logger.info("kv reattach on %s: %d %s", host,
                                resp.status, body[:200])
        except Exception as e:
            logger.info("kv reattach on %s skipped: %s", host, e)

    async def _supervise_crashes(self) -> None:
        """One supervisor pass: replicas whose process exited (or that
        failed health_fail_threshold consecutive probes) are replaced
        by standby promotion NOW — not on the reconciler's schedule."""
        threshold = self.recycle.health_fail_threshold
        for cid, comp in list(self.state.items()):
            for replica in list(comp.replicas):
                if id(replica) in self._recycling:
                    continue
                handle: _Proc = replica.handle
                if handle is None:
                    continue
                if handle.process.returncode is not None:
                    self._begin_promotion(replica, "process_exit")
                    await self._promote_standby(replica,
                                                "process_exit")
                    continue
                if not threshold:
                    continue
                if await self._probe_health(replica.host):
                    self._health_fails.pop(id(replica), None)
                    continue
                fails = self._health_fails.get(id(replica), 0) + 1
                self._health_fails[id(replica)] = fails
                if fails >= threshold:
                    logger.warning(
                        "replica %s failed %d consecutive health "
                        "probes; promoting its standby", replica.host,
                        fails)
                    self._begin_promotion(replica, "health_fail")
                    await self._promote_standby(replica,
                                                "health_fail")

    async def report_crash(self, replica: Replica) -> None:
        """Event-driven crash path (the router calls this when it
        evicts a dead replica): the corpse leaves rotation
        synchronously, promotion runs as a task so the reporting
        request keeps failing over without waiting for a spawn."""
        comp = self.state.get(replica.component_id)
        if comp is None or replica not in comp.replicas \
                or id(replica) in self._recycling:
            return
        self._begin_promotion(replica, "crash_report")
        asyncio.ensure_future(
            self._promote_standby(replica, "crash_report"))

    def _begin_promotion(self, replica: Replica, trigger: str) -> None:
        """Synchronous half of a promotion (no await between check and
        effect, so concurrent reporters can't double-promote): corpse
        out of rotation, create reservation held until
        `_promote_standby` releases it.  The process itself is stopped
        in the async half with the normal SIGTERM-drain contract."""
        self._recycling.add(id(replica))
        comp = self.state.get(replica.component_id)
        if comp is not None and replica in comp.replicas:
            comp.replicas.remove(replica)
        self._health_fails.pop(id(replica), None)
        key = (replica.component_id, replica.revision)
        self._creating[key] = self._creating.get(key, 0) + 1

    async def _promote_standby(self, replica: Replica,
                               trigger: str) -> None:
        """Async half: activate the armed standby (or cold respawn) and
        pin the failover timeline.  `_begin_promotion` ran first."""
        loop = asyncio.get_running_loop()
        cid, rev = replica.component_id, replica.revision
        t0 = loop.time()
        phases: Dict[str, float] = {}
        outcome, promoted_host = "promoted", None
        try:
            handle: _Proc = replica.handle
            if handle is not None:
                # Out of rotation already (no new traffic); now stop
                # the process with the normal drain contract — SIGTERM
                # (in-flight work gets its grace), escalating to
                # SIGKILL past TERM_GRACE_S.  A crashed process costs
                # nothing here (wait returns immediately); a
                # misdiagnosed-alive one gets to drain instead of
                # losing its in-flight inference to an instant kill.
                try:
                    await self._terminate(handle.process)
                except Exception:
                    pass
            dead_rc = (handle.process.returncode
                       if handle is not None else None)
            standby = self._pop_standby((cid, rev))
            # Bridge the promotion gap for waiting requests: the dead
            # replica is out of rotation and the successor is not in
            # yet.
            self.announce_swap(cid, (self.recycle.announce_budget_s
                                     if self.recycle is not None
                                     else 30.0))
            try:
                if standby is not None:
                    t_act = loop.time()
                    try:
                        await asyncio.wait_for(
                            self._activate_standby(standby),
                            READY_TIMEOUT_S)
                        promoted_host = standby.host
                    except asyncio.CancelledError:
                        # Shutdown mid-promotion: the standby is
                        # popped from the pool and outside
                        # self.state — reap it or it orphans.
                        await asyncio.shield(
                            self._terminate(standby.handle.process))
                        raise
                    except Exception:
                        logger.exception(
                            "promotion activate of %s failed; cold "
                            "respawn", standby.host)
                        await asyncio.shield(
                            self._terminate(standby.handle.process))
                        standby = None
                    phases["activate_s"] = round(
                        loop.time() - t_act, 3)
                if standby is None:
                    outcome = "cold_respawn"
                    t_spawn = loop.time()
                    successor = await self.create_replica(
                        cid, rev,
                        handle.spec if handle is not None else None,
                        placement=replica.placement,
                        minimal_warmup=True)
                    promoted_host = successor.host
                    phases["respawn_s"] = round(
                        loop.time() - t_spawn, 3)
            finally:
                self.clear_swap(cid)
            if promoted_host is not None:
                # Crash failover: the corpse's flock auto-released on
                # death, so the successor can adopt its durable KV
                # generation — the returning conversation faults back
                # instead of paying a full re-prefill.  Best-effort,
                # after the window clears.
                await self._kv_reattach(promoted_host)
            phases["total_s"] = round(loop.time() - t0, 3)
            self.promotions += 1
            obs.lifecycle_promotions_total().labels(
                trigger=trigger, outcome=outcome).inc()
            obs.lifecycle_phase_ms().labels(phase="promote").observe(
                (loop.time() - t0) * 1000.0)
            self.flight_recorder.record({
                "kind": "replica_failover",
                "component": cid, "revision": rev,
                "trigger": trigger,
                "dead_host": replica.host,
                "dead_rc": dead_rc,
                "outcome": outcome,
                "promoted_host": promoted_host,
                "phases": phases,
            }, pin="replica_failover")
            logger.warning(
                "replica %s of %s failed (%s): %s -> %s in %.2fs",
                replica.host, cid, trigger, outcome, promoted_host,
                phases["total_s"])
        except Exception:
            # Promotion is best-effort: on total failure the
            # reconciler's next pass restores capacity.
            logger.exception("standby promotion for %s failed", cid)
            obs.lifecycle_promotions_total().labels(
                trigger=trigger, outcome="failed").inc()
        finally:
            key = (cid, rev)
            n = self._creating.get(key, 1) - 1
            if n <= 0:
                self._creating.pop(key, None)
            else:
                self._creating[key] = n
            self._recycling.discard(id(replica))

    def _set_pool_gauge(self, cid: str) -> None:
        obs.lifecycle_standby_pool().labels(component=cid).set(
            float(sum(len(pool)
                      for (c, _r), pool in self._standbys.items()
                      if c == cid)))

    def _maintain_standby_pool(self) -> None:
        """Arm standbys per component (for the latest revision a
        serving replica carries) up to the component's pool target
        (default 1; the predictive autoscaler pre-arms deeper ahead
        of a forecast capacity gap): recycles then skip the spawn
        phase, crash promotion always has a warm successor, and a
        predicted traffic step actuates as activations instead of
        cold spawns.  Spawning runs as background tasks — arming must
        never block the supervisor tick."""
        for cid, comp in list(self.state.items()):
            if not comp.replicas:
                continue
            replica = comp.replicas[-1]
            handle: _Proc = replica.handle
            if handle is None or not self._standby_capable(handle.spec):
                continue
            key = (cid, replica.revision)
            want = self._standby_targets.get(cid, 1)
            have = len(self._standbys.get(key, ())) + \
                self._standby_spawning.get(key, 0)
            for _ in range(max(0, want - have)):
                self._standby_spawning[key] = \
                    self._standby_spawning.get(key, 0) + 1
                asyncio.ensure_future(self._arm_standby(
                    key, handle.spec, replica.placement))

    async def _arm_standby(self, key: tuple, spec, placement) -> None:
        cid, rev = key
        try:
            standby = await self.create_replica(
                cid, rev, spec, placement=placement, standby=True)
        except Exception:
            logger.exception("arming standby for %s failed", cid)
            return
        finally:
            n = self._standby_spawning.get(key, 1) - 1
            if n <= 0:
                self._standby_spawning.pop(key, None)
            else:
                self._standby_spawning[key] = n
        comp = self.state.get(cid)
        if comp is None or not any(r.revision == rev
                                   for r in comp.replicas):
            # The component (or this revision) retired while the
            # standby armed — reap, don't leak.
            await self._terminate(standby.handle.process)
            return
        self._standbys.setdefault(key, []).append(standby)
        self._set_pool_gauge(cid)
        logger.info("standby armed for %s rev=%s at %s (pool %d/%d)",
                    cid, rev[:8], standby.host,
                    len(self._standbys[key]),
                    self._standby_targets.get(cid, 1))

    def _reap_orphan_standbys(self) -> None:
        """Standbys whose component/revision no longer serves (scale
        to zero, canary retired, rollback) are torn down, dead pool
        processes are dropped (the next tick re-arms), and pools
        deeper than their target — a pre-arm whose predicted step
        never came, or already actuated — shrink back."""
        for key, pool in list(self._standbys.items()):
            cid, rev = key
            comp = self.state.get(cid)
            wanted = comp is not None and any(
                r.revision == rev for r in comp.replicas)
            want = self._standby_targets.get(cid, 1) if wanted else 0
            keep: List[Replica] = []
            for standby in pool:
                alive = standby.handle.process.returncode is None
                if alive and len(keep) < want:
                    keep.append(standby)
                    continue
                if alive:
                    asyncio.ensure_future(
                        self._terminate(standby.handle.process))
            if keep:
                self._standbys[key] = keep
            else:
                self._standbys.pop(key, None)
            self._set_pool_gauge(cid)

    async def reap_standbys(self, component_id: str,
                            revision: Optional[str] = None) -> None:
        """Immediate teardown hook for the reconciler/rollout: a
        retired (or quarantined) revision's armed standbys must not
        survive to be promoted later."""
        for key, pool in list(self._standbys.items()):
            cid, rev = key
            if cid != component_id:
                continue
            if revision is not None and rev != revision:
                continue
            self._standbys.pop(key, None)
            self._set_pool_gauge(cid)
            for standby in pool:
                await self._terminate(standby.handle.process)

    async def delete_replica(self, replica: Replica) -> None:
        comp = self.state.get(replica.component_id)
        if comp and replica in comp.replicas:
            comp.replicas.remove(replica)
        self._health_fails.pop(id(replica), None)
        handle: _Proc = replica.handle
        if handle is not None:
            await self._terminate(handle.process)
        logger.info("replica down: %s at %s",
                    replica.component_id, replica.host)

    @staticmethod
    async def _terminate(process) -> None:
        if process.returncode is not None:
            return
        process.terminate()
        try:
            await asyncio.wait_for(process.wait(), TERM_GRACE_S)
        except asyncio.TimeoutError:
            process.kill()
            await process.wait()

    async def shutdown(self):
        if self._watchdog is not None:
            self._watchdog.cancel()
            try:
                await self._watchdog
            except (asyncio.CancelledError, Exception):
                pass
            self._watchdog = None
        # Armed standbys live outside self.state — reap them first or
        # they orphan (an exclusive-device orphan holds the chip).
        for key, pool in list(self._standbys.items()):
            self._standbys.pop(key, None)
            for standby in pool:
                await self._terminate(standby.handle.process)
        for comp in list(self.state.values()):
            for replica in list(comp.replicas):
                await self.delete_replica(replica)
