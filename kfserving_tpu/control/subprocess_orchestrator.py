"""SubprocessOrchestrator: replicas are real OS processes.

The reference's replicas are pods created by Knative from the ksvc the
reconciler writes (reference ksvc_reconciler.go:153-187); the
single-host TPU equivalent is one process per replica, exec'd from the
per-framework entrypoint module registered in the cluster config
(`python -m kfserving_tpu.predictors.<fw> --model_name ... --model_dir
... --http_port ...` — the same arg convention the reference's
predictor specs build, predictor_sklearn.go:77-96).

Readiness mirrors the pod readiness probe: the replica joins the
router's rotation only after its health route answers.  Deletion is
SIGTERM (the server's signal handler drains in-flight work) escalating
to SIGKILL.

TPU note: on a single chip only one process can own the device; either
give each JAX replica a distinct mesh slice via env (TPU_VISIBLE_DEVICES
/ JAX_PLATFORMS) through `env_overrides`, or keep max_replicas=1 for
chip-owning predictors.  CPU frameworks (sklearn/xgb/...) scale freely.
"""

import asyncio
import logging
import os
import socket
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kfserving_tpu.control.clusterconfig import ClusterConfig
from kfserving_tpu.control.orchestrator import Replica, _ComponentState

logger = logging.getLogger("kfserving_tpu.control.subprocess")

READY_TIMEOUT_S = 120.0
TERM_GRACE_S = 10.0


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@dataclass
class _Proc:
    process: asyncio.subprocess.Process
    port: int


class SubprocessOrchestrator:
    """Actuation backend that execs one server process per replica."""

    def __init__(self, cluster_config: Optional[ClusterConfig] = None,
                 env_overrides: Optional[Dict[str, str]] = None,
                 host: str = "127.0.0.1",
                 credentials=None):
        self.cluster_config = cluster_config or ClusterConfig()
        self.env_overrides = env_overrides or {}
        self.host = host
        # CredentialStore: per-service-account env injected into replica
        # processes (reference credential builder injects into containers).
        self.credentials = credentials
        self.state: Dict[str, _ComponentState] = {}

    def replicas(self, component_id: str) -> List[Replica]:
        return list(self.state.get(component_id,
                                   _ComponentState()).replicas)

    # -- spec -> argv -------------------------------------------------------
    def _command(self, component_id: str, spec, port: int) -> List[str]:
        from kfserving_tpu.control.spec import (
            ExplainerSpec,
            PredictorSpec,
            TransformerSpec,
        )

        isvc_name = component_id.split("/")[1]
        if isinstance(spec, (TransformerSpec, ExplainerSpec)) and \
                getattr(spec, "command", None):
            return list(spec.command) + ["--http_port", str(port)]
        if isinstance(spec, PredictorSpec):
            if spec.framework == "custom":
                if not spec.command:
                    raise ValueError(
                        "custom predictor needs an explicit command")
                return list(spec.command) + ["--http_port", str(port)]
            runtime = self.cluster_config.runtime_for(spec.framework)
            argv = [sys.executable, "-m", runtime["module"],
                    "--model_name", isvc_name,
                    "--model_dir", spec.storage_uri,
                    "--http_port", str(port)]
            if spec.container_concurrency:
                argv += ["--container_concurrency",
                         str(spec.container_concurrency)]
            if spec.batcher is not None:
                argv += ["--max_batch_size",
                         str(spec.batcher.max_batch_size),
                         "--max_latency_ms",
                         str(spec.batcher.max_latency_ms)]
            if spec.multi_model:
                argv += ["--multi_model"]
            return argv
        raise ValueError(
            f"subprocess orchestrator cannot run component spec "
            f"{type(spec).__name__} without an explicit command")

    # -- lifecycle ----------------------------------------------------------
    async def create_replica(self, component_id: str, revision: str,
                             spec, placement=None) -> Replica:
        port = _free_port(self.host)
        argv = self._command(component_id, spec, port)
        env = dict(os.environ)
        # The package must be importable from the child even when not
        # pip-installed.
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (
            repo_root + os.pathsep + env.get("PYTHONPATH", "")).rstrip(
                os.pathsep)
        if self.credentials is not None:
            env.update(self.credentials.build_env(
                getattr(spec, "service_account_name", "default")))
        if placement is not None:
            # Slice discovery env — the TPU analogue of the reference's
            # injected nodeSelector (accelerator_injector.go:38-44).
            env.update(placement.env())
        env.update(self.env_overrides)
        logger.info("spawning replica %s rev=%s: %s",
                    component_id, revision[:8], " ".join(argv))
        process = await asyncio.create_subprocess_exec(
            *argv, env=env,
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.DEVNULL)
        host = f"{self.host}:{port}"
        try:
            await self._wait_ready(process, host)
        except Exception:
            await self._terminate(process)
            raise
        replica = Replica(component_id, revision, host,
                          handle=_Proc(process, port), placement=placement)
        self.state.setdefault(component_id,
                              _ComponentState()).replicas.append(replica)
        return replica

    async def _wait_ready(self, process, host: str) -> None:
        """Poll the liveness route until it answers (readiness probe)."""
        import aiohttp

        deadline = asyncio.get_running_loop().time() + READY_TIMEOUT_S
        url = f"http://{host}/"
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=2.0)) as session:
            while True:
                if process.returncode is not None:
                    raise RuntimeError(
                        f"replica process exited rc={process.returncode} "
                        f"before becoming ready")
                try:
                    async with session.get(url) as resp:
                        if resp.status == 200:
                            return
                except Exception:
                    pass
                if asyncio.get_running_loop().time() > deadline:
                    raise TimeoutError(
                        f"replica at {host} not ready after "
                        f"{READY_TIMEOUT_S}s")
                await asyncio.sleep(0.1)

    async def delete_replica(self, replica: Replica) -> None:
        comp = self.state.get(replica.component_id)
        if comp and replica in comp.replicas:
            comp.replicas.remove(replica)
        handle: _Proc = replica.handle
        if handle is not None:
            await self._terminate(handle.process)
        logger.info("replica down: %s at %s",
                    replica.component_id, replica.host)

    @staticmethod
    async def _terminate(process) -> None:
        if process.returncode is not None:
            return
        process.terminate()
        try:
            await asyncio.wait_for(process.wait(), TERM_GRACE_S)
        except asyncio.TimeoutError:
            process.kill()
            await process.wait()

    async def shutdown(self):
        for comp in list(self.state.values()):
            for replica in list(comp.replicas):
                await self.delete_replica(replica)
