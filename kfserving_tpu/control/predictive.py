"""Feed-forward predictive scaling: close the loop from burn rate to
capacity.

The reactive autoscaler (autoscaler.py) sizes replicas from
*instantaneous* in-flight concurrency — by the time a traffic step
shows up in that gauge, the p99 objective is already breached, and
when capacity physically cannot arrive in time there is no
graceful-degradation path at all.  InferLine (arXiv:1812.01776) shows
latency-objective-driven provisioning planned over the whole pipeline
beats per-stage reactivity; this module is that planner for the
single-host fabric:

- **Signals** — the SLO engine's multi-window burn rates evaluated at
  the ROUTER's vantage point (the per-revision request series the
  router feeds per upstream attempt: `kfserving_tpu_revision_*`),
  plus the router's per-component arrival-rate counters.  The burn
  rate is the leading edge: it trips within one short window of a
  step, long before the in-flight average window turns over.
- **Sizing** — Little's law over observed traffic: required
  concurrency = arrival rate x observed service time; replicas =
  ceil(required / (target_util x per-replica concurrency)).  Observed
  service time comes from the latency histogram (bucket-midpoint
  mean), so queue growth inflates the estimate and the plan
  over-provisions exactly when the queue is the problem.
- **Actuation** — the standby pool is PRE-ARMED to the predicted size
  (`set_standby_target`), so the scale-up the autoscaler then issues
  actuates as PR 7's one-tick standby activation, not a cold spawn.
- **Chains** — an InferenceService with a transformer is provisioned
  JOINTLY: the entry component's arrival rate floors every downstream
  component's arrival (each transformer request fans a predictor call
  through the ingress direct lane), so the predictor scales with the
  step the transformer just saw instead of waiting to measure it
  (the serverless-dataflow chain view, arXiv:2007.05832).
- **Brownout** — when the predicted gap exceeds what current replicas
  + armed standbys can cover (or max_replicas caps it), the router's
  BrownoutController sheds the lowest-priority traffic with explicit
  retriable 503s instead of blowing p99 for everyone; exit is
  automatic as the burn rate recovers.

Every decision (inputs, predicted gap, action) is pinned into the
supervisor flight recorder — federated at `/debug/flightrecorder` as
replica="supervisor" — and counted in
`kfserving_tpu_autoscaler_decisions_total`.
"""

import logging
import math
import time
from typing import Any, Dict, List, Optional, Tuple

from kfserving_tpu.observability import REGISTRY
from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.metrics import (
    REVISION_LATENCY_SERIES,
    REVISION_REQUESTS_SERIES,
)
from kfserving_tpu.observability.monitoring import FlightRecorder
from kfserving_tpu.observability.monitoring.slo import (
    SLOEngine,
    SLOObjective,
    _window_label,
    objectives_from_env,
)

logger = logging.getLogger("kfserving_tpu.control.predictive")

# Control-plane burn windows: much shorter than the replica-side
# default (60/300 s) — the control loop must see a step within a few
# ticks, and a single-spike false positive costs one pre-armed
# standby, not a page.
DEFAULT_WINDOWS_S = (10.0, 60.0)
DEFAULT_TARGET_UTIL = 0.8
DEFAULT_BURN_EXIT = 1.0
DEFAULT_EXIT_TICKS = 3
DEFAULT_MAX_BROWNOUT_LEVEL = 2
# When a component declares no containerConcurrency the reactive
# autoscaler falls back to its target concurrency; the sizing model
# needs the same per-replica capacity assumption.
DEFAULT_FALLBACK_CONCURRENCY = 4
# Slope-aware gap sizing (ISSUE 17, off by default): how far ahead
# the history detector's latency trend slope is projected when
# inflating the observed service time.
DEFAULT_SLOPE_HORIZON_S = 15.0
# The watched latency series whose trend slope feeds the projection
# (ms of p99 per second) — the router's own per-revision view first,
# the replicas' request-latency view as fallback.
SLOPE_SERIES_NAMES = (
    "kfserving_tpu_revision_request_ms_p99",
    "kfserving_tpu_request_latency_ms_p99",
)


def ensure_flight_recorder(orchestrator) -> Optional[FlightRecorder]:
    """The supervisor flight recorder for decision evidence.  The
    subprocess orchestrator carries one (PR 7 failover timelines);
    in-process/fake orchestrators get one attached on first use so
    the router's replica="supervisor" federation serves the decision
    trail on every backend."""
    recorder = getattr(orchestrator, "flight_recorder", None)
    if recorder is None:
        recorder = FlightRecorder.from_env()
        try:
            orchestrator.flight_recorder = recorder
        except Exception:  # frozen/slotted test double: no evidence
            return None
    return recorder


class PredictiveScaler:
    """The feed-forward half of the autoscaler: burn-driven sizing,
    standby pre-arming, and brownout entry/exit.  One instance per
    control plane; the Autoscaler calls `observe()` once per tick and
    `desired_replicas()` / `evaluate_brownout()` per component/model.
    """

    def __init__(self, controller, router,
                 objectives: Optional[Dict[str, SLOObjective]] = None,
                 windows_s: Tuple[float, ...] = DEFAULT_WINDOWS_S,
                 burn_alert: Optional[float] = None,
                 burn_exit: float = DEFAULT_BURN_EXIT,
                 exit_ticks: int = DEFAULT_EXIT_TICKS,
                 target_util: float = DEFAULT_TARGET_UTIL,
                 brownout=None,
                 max_brownout_level: int = DEFAULT_MAX_BROWNOUT_LEVEL,
                 slope_aware: bool = False,
                 slope_horizon_s: float = DEFAULT_SLOPE_HORIZON_S):
        self.controller = controller
        self.router = router
        self.brownout = brownout
        self.target_util = target_util
        # Slope-aware gap sizing (ISSUE 17): when on, the history
        # detector's trend-slope gauge inflates the observed service
        # time by the projected latency growth over `slope_horizon_s`
        # — capacity for where the latency is HEADING, one window
        # before the mean catches up.  Off (the default) leaves the
        # sizing math exactly as before.
        self.slope_aware = slope_aware
        self.slope_horizon_s = slope_horizon_s
        self.burn_exit = burn_exit
        self.exit_ticks = max(1, int(exit_ticks))
        self.max_brownout_level = max_brownout_level
        if objectives is None:
            objectives = objectives_from_env()
        # Burn-rate evaluation at the router's vantage point: same
        # multi-window math as the replicas' engines, over the
        # per-revision series the router records per upstream attempt.
        # export_gauges=False — the replicas own the slo_* gauge
        # children for their models; this engine reports through the
        # decision records instead.
        slo_kwargs: Dict[str, Any] = dict(
            objectives=objectives, windows_s=windows_s,
            total_series=REVISION_REQUESTS_SERIES,
            latency_series=REVISION_LATENCY_SERIES,
            export_gauges=False)
        if burn_alert is not None:
            slo_kwargs["burn_alert"] = burn_alert
        self.slo = SLOEngine([REGISTRY], **slo_kwargs)
        # (monotonic t, {gauge_key: cumulative router request count}).
        self._count_snaps: List[Tuple[float, Dict[str, int]]] = []
        # Last sized plan per component id (one tick's cache, consumed
        # by evaluate_brownout after the components scaled).
        self._plans: Dict[str, Dict[str, Any]] = {}
        # Per-model brownout bookkeeping.
        self._calm_ticks: Dict[str, int] = {}
        self._last_sized: Dict[str, int] = {}
        # Components whose standby pool this loop pre-armed: the
        # target must be handed back to the backend default when the
        # loop disengages, or one transient spike parks warm
        # processes at peak depth forever.
        self._pre_armed: set = set()
        self.decisions: List[Dict[str, Any]] = []

    @property
    def enabled(self) -> bool:
        return self.slo.enabled

    # -- signal collection --------------------------------------------------
    def observe(self, now: Optional[float] = None) -> None:
        """One tick's signal snapshot: burn rates (SLO engine tick)
        plus the router's per-component arrival counters."""
        now = time.monotonic() if now is None else now
        self.slo.tick(now)
        # OFFERED load (counted before the router's brownout gate):
        # shedding must not erase the demand signal that justified it.
        self._count_snaps.append((now, dict(self.router.offered_count)))
        horizon = now - self.slo.windows_s[-1]
        while len(self._count_snaps) > 2 and \
                self._count_snaps[1][0] <= horizon:
            self._count_snaps.pop(0)
        if self.brownout is not None:
            for model in self._models_with_traffic():
                service_s = self.service_estimate_s(model)
                if service_s is not None:
                    self.brownout.update_estimate(model, service_s)

    def _models_with_traffic(self) -> List[str]:
        if not self._count_snaps:
            return []
        models = set()
        for key in self._count_snaps[-1][1]:
            parts = key.split("/")
            if len(parts) == 3:
                models.add(parts[1])
        return sorted(models)

    def arrival_rate(self, gauge_key: str,
                     window_s: Optional[float] = None) -> float:
        """Requests/s at one router gauge over the SHORT window (the
        leading signal — by design it reacts within one window of a
        step)."""
        if not self._count_snaps:
            return 0.0
        window_s = window_s or self.slo.windows_s[0]
        now_t, now_counts = self._count_snaps[-1]
        base_t, base_counts = self._count_snaps[0]
        for t, counts in self._count_snaps:
            if t <= now_t - window_s:
                base_t, base_counts = t, counts
            else:
                break
        dt = now_t - base_t
        if dt <= 0:
            return 0.0
        delta = now_counts.get(gauge_key, 0) - \
            base_counts.get(gauge_key, 0)
        return max(0.0, delta / dt)

    def service_estimate_s(self, model: str) -> Optional[float]:
        """Observed mean service time (seconds) from the router's
        per-revision latency histogram over the short window: bucket-
        midpoint weighted mean (the registry histogram keeps no sum).
        Queue wait is included on purpose — when the queue grows, the
        plan must grow with it."""
        snaps = self.slo._snapshots
        if not snaps:
            return None
        now_t, now_snap = snaps[-1]
        base = self.slo._baseline(now_t - self.slo.windows_s[0])
        cur = now_snap.get(model)
        if cur is None or cur.get("lat_counts") is None:
            return None
        counts = list(cur["lat_counts"])
        buckets = cur["lat_buckets"]
        prev = (base or {}).get(model)
        if prev is not None and prev.get("lat_counts") is not None \
                and len(prev["lat_counts"]) == len(counts):
            counts = [a - b for a, b in zip(counts,
                                            prev["lat_counts"])]
        total = sum(c for c in counts if c > 0)
        if total <= 0 or not buckets:
            return None
        weighted = 0.0
        lower = 0.0
        for bound, count in zip(buckets, counts):
            if count > 0:
                weighted += count * (lower + bound) / 2.0
            lower = bound
        if len(counts) > len(buckets) and counts[-1] > 0:
            weighted += counts[-1] * buckets[-1] * 1.5  # +Inf bucket
        return (weighted / total) / 1000.0

    def _latency_slope_ms_per_s(self, model: str) -> Optional[float]:
        """The history detector's trend slope for this model's watched
        latency-p99 series (ms per second), worst series wins.  None
        when no history subsystem exports the gauge — the slope-aware
        path then degrades to exactly the slope-off sizing."""
        fam = REGISTRY.family(obs.TREND_SLOPE_SERIES)
        if fam is None:
            return None
        worst: Optional[float] = None
        for labels, child in fam.samples():
            if labels.get("series") not in SLOPE_SERIES_NAMES:
                continue
            if labels.get("model") != model:
                continue
            if worst is None or child.value > worst:
                worst = child.value
        return worst

    def burn_state(self, model: str
                   ) -> Tuple[bool, Dict[str, Dict[str, float]]]:
        """(fast_burn, burn_rates) for a model.  Fast burn = the
        SHORTEST window burns past the alert threshold while the
        longest is not already cooling below it — the leading-edge
        trend, not the sustained multi-window page condition."""
        report = self.slo._last_report or {}
        entry = (report.get("models") or {}).get(model)
        if not entry:
            return False, {}
        rates = entry.get("burn_rates", {})
        short_l = _window_label(self.slo.windows_s[0])
        long_l = _window_label(self.slo.windows_s[-1])
        for component_rates in rates.values():
            short = component_rates.get(short_l)
            long_r = component_rates.get(long_l, 0.0)
            if short is not None and short > self.slo.burn_alert \
                    and short >= long_r:
                return True, rates
        return False, rates

    # -- sizing -------------------------------------------------------------
    def desired_replicas(self, name: str, isvc, cname: str, comp,
                         cid: str, current: int) -> int:
        """Feed-forward replica count for one component (0 = not
        engaged; the reactive signal rules alone).  Side effects: the
        standby pool is pre-armed toward the prediction and the sizing
        decision lands in the flight recorder."""
        if not self.enabled:
            return 0
        objective = self.slo.objective_for(name)
        if objective is None:
            return 0
        fast_burn, burn_rates = self.burn_state(name)
        gauge_key = f"router/{name}/{cname}"
        arrival = self.arrival_rate(gauge_key)
        # Chain-joint provisioning: the entry component's arrival
        # floors every downstream component's — the step the
        # transformer just absorbed reaches the predictor one proxy
        # hop later, so provision it NOW, not after it is measured.
        entry = self.router._entry_component(isvc, "predict")
        if cname != entry:
            arrival = max(arrival,
                          self.arrival_rate(f"router/{name}/{entry}"))
        service_s = self.service_estimate_s(name)
        slope_ms_per_s = None
        if self.slope_aware and service_s:
            # Leading input (ISSUE 17): project the observed service
            # time to where the trend says latency will BE one
            # horizon out.  Only a rising slope inflates — a falling
            # one must not shrink capacity below what is measured.
            slope_ms_per_s = self._latency_slope_ms_per_s(name)
            if slope_ms_per_s is not None and slope_ms_per_s > 0:
                service_s = service_s + (slope_ms_per_s / 1000.0) \
                    * self.slope_horizon_s
        plan: Dict[str, Any] = {
            "component": cid,
            "arrival_per_s": round(arrival, 3),
            "service_ms": (round(service_s * 1000.0, 3)
                           if service_s else None),
            "burn_rates": burn_rates,
            "fast_burn": fast_burn,
            "current": current,
            "max_replicas": comp.max_replicas,
        }
        if self.slope_aware:
            plan["slope_ms_per_s"] = (
                round(slope_ms_per_s, 4)
                if slope_ms_per_s is not None else None)
            plan["slope_horizon_s"] = self.slope_horizon_s
        # The sizing itself runs UNGATED (brownout needs the demand
        # picture even after shedding calmed the latency series);
        # only the scaling/pre-arm actuation is gated on fast burn.
        required = 0
        if arrival > 0 and service_s:
            per_replica = comp.container_concurrency \
                or DEFAULT_FALLBACK_CONCURRENCY
            required_conc = arrival * service_s  # Little's law
            required = max(1, math.ceil(
                required_conc / (self.target_util * per_replica)))
        plan["required"] = required
        self._plans[cid] = plan
        if not fast_burn and not self._engaged(name):
            obs.autoscaler_predicted_replicas().labels(
                component=cid).set(0.0)
            self._last_sized.pop(cid, None)
            # Disengaging (spike ended, burn calm): any pre-armed
            # pool depth goes back to the backend default NOW — the
            # `required <= current` reset below may never be reached
            # when arrival collapsed with the spike.
            self._reset_pool(cid)
            return 0
        if required == 0:
            return 0
        obs.autoscaler_predicted_replicas().labels(
            component=cid).set(float(required))
        sized = min(required, comp.max_replicas)
        if required > current and \
                self._last_sized.get(cid) != required:
            self._last_sized[cid] = required
            self._pre_arm(cid, required, current, plan)
        elif required <= current:
            self._last_sized.pop(cid, None)
            self._reset_pool(cid)
        return sized

    def _engaged(self, model: str) -> bool:
        """Stay engaged while a brownout is active: shedding calms
        the burn rate by construction, and releasing the predicted
        replica floor on that calm would scale down into the very
        overload being shed."""
        return self.brownout is not None and \
            self.brownout.level(model) > 0

    def _pre_arm(self, cid: str, required: int, current: int,
                 plan: Dict[str, Any]) -> None:
        """Arm the standby pool toward the predicted gap and pin the
        sizing decision.  The scale-up itself is the autoscaler's
        (which now adopts armed standbys in _scale_revisions)."""
        gap = max(0, required - current)
        orch = self.controller.reconciler.orchestrator
        set_target = getattr(orch, "set_standby_target", None)
        action = "scale_up"
        if set_target is not None and gap > 0:
            set_target(cid, gap)
            self._pre_armed.add(cid)
            action = "pre_arm"
        self._record(dict(
            kind="predictive_scaling", action=action,
            predicted_gap=gap, standby_target=gap if
            action == "pre_arm" else None, **plan))

    def _reset_pool(self, cid: str) -> None:
        """Hand a pre-armed pool back to the backend default.  Target
        0 means "your own floor": the subprocess backend clamps back
        to its lifecycle default of 1 (crash failover always wants a
        warm successor), the in-process backend back to 0 (its pool
        exists only while pre-armed)."""
        if cid not in self._pre_armed:
            return
        self._pre_armed.discard(cid)
        orch = self.controller.reconciler.orchestrator
        set_target = getattr(orch, "set_standby_target", None)
        if set_target is not None:
            set_target(cid, 0)

    # -- brownout entry/exit ------------------------------------------------
    def evaluate_brownout(self, name: str, isvc) -> None:
        """Per-model brownout decision, after this tick's components
        were sized: enter/escalate while the predicted gap exceeds
        what replicas + armed standbys can cover, step back down as
        the burn rate recovers."""
        if self.brownout is None or not self.enabled:
            return
        if self.slo.objective_for(name) is None:
            return
        fast_burn, burn_rates = self.burn_state(name)
        orch = self.controller.reconciler.orchestrator
        gap = 0
        worst: Optional[Dict[str, Any]] = None
        for cname in isvc.components():
            cid = self.controller.reconciler.component_id(isvc, cname)
            plan = self._plans.get(cid)
            if not plan or not plan.get("required"):
                continue
            standby_count = getattr(orch, "standby_count",
                                    lambda c: 0)(cid)
            coverage = min(plan["required"],
                           plan["current"] + standby_count,
                           plan["max_replicas"])
            comp_gap = plan["required"] - coverage
            if comp_gap > gap:
                gap, worst = comp_gap, dict(plan,
                                            coverage=coverage)
        level = self.brownout.level(name)
        if fast_burn and gap > 0:
            self._calm_ticks.pop(name, None)
            new_level = min(level + 1, self.max_brownout_level)
            direction = self.brownout.set_level(name, new_level)
            if direction is not None:
                self._record({
                    "kind": "brownout", "model": name,
                    "action": ("brownout_enter" if direction == "enter"
                               else "brownout_escalate"),
                    "level": new_level,
                    "predicted_gap": gap,
                    "inputs": worst or {"burn_rates": burn_rates},
                }, component=name)
            return
        if level <= 0:
            self._calm_ticks.pop(name, None)
            return
        # Recovery hysteresis: the SHORT window must sit below the
        # exit threshold for exit_ticks consecutive ticks before each
        # step down.  While the predicted gap persists, recovery
        # stops at level 1 (shedding calms the admitted-traffic burn
        # by construction — a full exit on that calm would oscillate
        # the floodgates open and shut every few ticks); the final
        # exit to level 0 waits for the demand gap itself to clear.
        # Levels ABOVE 1 do step down under a calm burn even mid-gap:
        # escalation past the minimal shed is re-earned per tick, so
        # traffic that fits the remaining capacity is not shed a
        # moment longer than the burn justifies.
        short = 0.0
        for component_rates in burn_rates.values():
            short = max(short, component_rates.get(
                _window_label(self.slo.windows_s[0]), 0.0))
        if short >= self.burn_exit or (gap > 0 and level <= 1):
            self._calm_ticks[name] = 0
            return
        calm = self._calm_ticks.get(name, 0) + 1
        self._calm_ticks[name] = calm
        if calm < self.exit_ticks:
            return
        self._calm_ticks[name] = 0
        direction = self.brownout.set_level(name, level - 1)
        if direction is not None:
            self._record({
                "kind": "brownout", "model": name,
                "action": ("brownout_exit" if level - 1 == 0
                           else "brownout_recover"),
                "level": level - 1,
                "inputs": {"burn_rates": burn_rates,
                           "short_window_burn": short},
            }, component=name)

    # -- evidence -----------------------------------------------------------
    def _record(self, entry: Dict[str, Any],
                component: Optional[str] = None) -> None:
        action = entry.get("action", "decision")
        obs.autoscaler_decisions_total().labels(
            component=component or entry.get("component", ""),
            action=action).inc()
        self.decisions.append(entry)
        del self.decisions[:-256]  # bounded local trail
        recorder = ensure_flight_recorder(
            self.controller.reconciler.orchestrator)
        if recorder is not None:
            recorder.record(dict(entry), pin=entry["kind"])
        logger.info("predictive decision: %s", entry)
