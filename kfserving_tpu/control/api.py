"""Control-plane HTTP API: the K8s-apiserver surface of the framework.

The reference's clients (kubectl, the Python SDK) talk CRD objects to the
API server; the controller watches them (reference
api/kf_serving_client.py:89-380 drives CustomObjectsApi CRUD).  Here the
same CRUD surface is a small REST API directly over the in-process
Controller — apply is synchronous reconcile, so a successful response
already carries the resulting status.

Routes:

    GET    /healthz
    GET    /v1/inferenceservices
    POST   /v1/inferenceservices                      create-or-replace
    GET    /v1/inferenceservices/{ns}/{name}          {"spec","status"}
    PATCH  /v1/inferenceservices/{ns}/{name}          JSON merge-patch
    DELETE /v1/inferenceservices/{ns}/{name}
    GET    /v1/trainedmodels
    POST   /v1/trainedmodels
    GET    /v1/trainedmodels/{ns}/{name}
    DELETE /v1/trainedmodels/{ns}/{name}
    GET    /v1/secrets                                metadata only, no data
    POST   /v1/secrets                                create (+optional attach)
    DELETE /v1/secrets/{name}
    GET    /v1/serviceaccounts
    POST   /v1/serviceaccounts/{name}/secrets         attach existing secret

The secrets surface is the server side of the SDK's credential
registration (reference python/kfserving/kfserving/api/creds_utils.py:
create_secret + set_service_account against the K8s API); secret data is
write-only — list/read endpoints never return it.
"""

import asyncio
import json
import logging
from dataclasses import asdict
from typing import Any, Dict, Optional

from kfserving_tpu.control.controller import Controller
from kfserving_tpu.control.spec import InferenceService, TrainedModel
from kfserving_tpu.control.validation import ValidationError
from kfserving_tpu.server.http import HTTPServer, Request, Response, Router

logger = logging.getLogger("kfserving_tpu.control.api")


def _json(data: Any, status: int = 200) -> Response:
    return Response(json.dumps(data).encode(), status=status)


def _err(message: str, status: int) -> Response:
    return _json({"error": message}, status=status)


def merge_patch(base: Dict[str, Any], patch: Dict[str, Any]
                ) -> Dict[str, Any]:
    """RFC 7386 JSON merge-patch (null deletes a key)."""
    out = dict(base)
    for key, value in patch.items():
        if value is None:
            out.pop(key, None)
        elif isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = merge_patch(out[key], value)
        else:
            out[key] = value
    return out


class ControlAPI:
    def __init__(self, controller: Controller, http_port: int = 0,
                 credentials=None, credentials_path: Optional[str] = None):
        self.controller = controller
        self.http_port = http_port
        # CredentialStore shared with the orchestrators; mutations via the
        # secrets routes take effect on the next replica build and persist
        # to credentials_path when configured.
        self.credentials = credentials
        self.credentials_path = credentials_path
        self._persist_lock = asyncio.Lock()
        self.router = Router()
        self._register_routes()
        self.http_server = HTTPServer(self.router)

    def _register_routes(self):
        r = self.router
        r.add("GET", "/healthz", self._healthz)
        r.add("GET", "/v1/inferenceservices", self._list_isvc)
        r.add("POST", "/v1/inferenceservices", self._apply_isvc)
        r.add("GET", "/v1/inferenceservices/{ns}/{name}", self._get_isvc)
        r.add("PATCH", "/v1/inferenceservices/{ns}/{name}",
              self._patch_isvc)
        r.add("DELETE", "/v1/inferenceservices/{ns}/{name}",
              self._delete_isvc)
        r.add("GET", "/v1/trainedmodels", self._list_tm)
        r.add("POST", "/v1/trainedmodels", self._apply_tm)
        r.add("GET", "/v1/trainedmodels/{ns}/{name}", self._get_tm)
        r.add("DELETE", "/v1/trainedmodels/{ns}/{name}", self._delete_tm)
        r.add("GET", "/v1/secrets", self._list_secrets)
        r.add("POST", "/v1/secrets", self._create_secret)
        r.add("DELETE", "/v1/secrets/{name}", self._delete_secret)
        r.add("GET", "/v1/serviceaccounts", self._list_service_accounts)
        r.add("POST", "/v1/serviceaccounts/{name}/secrets",
              self._attach_secret)

    async def start_async(self, host: str = "127.0.0.1"):
        await self.http_server.start(host, self.http_port)
        self.http_port = self.http_server.port

    async def stop_async(self):
        await self.http_server.stop()

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def _decode(req: Request) -> Dict[str, Any]:
        try:
            data = json.loads(req.body or b"{}")
        except ValueError as e:
            raise ValidationError(f"invalid JSON body: {e}")
        if not isinstance(data, dict):
            raise ValidationError("body must be a JSON object")
        return data

    def _status_dict(self, name: str, ns: str) -> Optional[dict]:
        status = self.controller.status_of(name, ns)
        if status is None:
            return None
        out = asdict(status)
        out["ready"] = status.ready
        return out

    # -- handlers: InferenceService -----------------------------------------
    async def _healthz(self, req: Request) -> Response:
        return _json({"status": "ok",
                      "inferenceservices": len(self.controller.specs)})

    async def _list_isvc(self, req: Request) -> Response:
        items = []
        for key, isvc in self.controller.specs.items():
            status = self._status_dict(isvc.name, isvc.namespace)
            items.append({
                "name": isvc.name,
                "namespace": isvc.namespace,
                "ready": bool(status and status["ready"]),
            })
        return _json({"items": items})

    async def _apply_isvc(self, req: Request) -> Response:
        try:
            data = self._decode(req)
            isvc = InferenceService.from_dict(data)
            existing = self.controller.get(isvc.name, isvc.namespace)
            await self.controller.apply(isvc)
        except (ValidationError, TypeError, KeyError, ValueError) as e:
            return _err(str(e), 422)
        return _json(
            {"name": isvc.name, "namespace": isvc.namespace,
             "status": self._status_dict(isvc.name, isvc.namespace)},
            status=200 if existing is not None else 201)

    async def _get_isvc(self, req: Request) -> Response:
        ns, name = req.path_params["ns"], req.path_params["name"]
        isvc = self.controller.get(name, ns)
        if isvc is None:
            return _err(f"inference service {ns}/{name} not found", 404)
        return _json({"spec": isvc.to_dict(),
                      "status": self._status_dict(name, ns)})

    async def _patch_isvc(self, req: Request) -> Response:
        ns, name = req.path_params["ns"], req.path_params["name"]
        isvc = self.controller.get(name, ns)
        if isvc is None:
            return _err(f"inference service {ns}/{name} not found", 404)
        try:
            patch = self._decode(req)
            merged = merge_patch(isvc.to_dict(), patch)
            merged["name"], merged["namespace"] = name, ns
            updated = InferenceService.from_dict(merged)
            await self.controller.apply(updated)
        except (ValidationError, TypeError, KeyError, ValueError) as e:
            return _err(str(e), 422)
        return _json({"name": name, "namespace": ns,
                      "status": self._status_dict(name, ns)})

    async def _delete_isvc(self, req: Request) -> Response:
        ns, name = req.path_params["ns"], req.path_params["name"]
        if self.controller.get(name, ns) is None:
            return _err(f"inference service {ns}/{name} not found", 404)
        await self.controller.remove(name, ns)
        return _json({"deleted": f"{ns}/{name}"})

    # -- handlers: TrainedModel ---------------------------------------------
    async def _list_tm(self, req: Request) -> Response:
        items = [{"name": tm.name, "namespace": tm.namespace,
                  "inferenceService": tm.inference_service}
                 for tm in self.controller.trained_models.values()]
        return _json({"items": items})

    async def _apply_tm(self, req: Request) -> Response:
        try:
            data = self._decode(req)
            tm = TrainedModel(**data)
            result = await self.controller.apply_trained_model(tm)
        except (ValidationError, TypeError) as e:
            return _err(str(e), 422)
        return _json({"name": tm.name, "namespace": tm.namespace,
                      **result}, status=201)

    async def _get_tm(self, req: Request) -> Response:
        ns, name = req.path_params["ns"], req.path_params["name"]
        tm = self.controller.trained_models.get(f"{ns}/{name}")
        if tm is None:
            return _err(f"trained model {ns}/{name} not found", 404)
        return _json({"spec": asdict(tm)})

    async def _delete_tm(self, req: Request) -> Response:
        ns, name = req.path_params["ns"], req.path_params["name"]
        if f"{ns}/{name}" not in self.controller.trained_models:
            return _err(f"trained model {ns}/{name} not found", 404)
        await self.controller.remove_trained_model(name, ns)
        return _json({"deleted": f"{ns}/{name}"})

    # -- handlers: credentials ----------------------------------------------
    async def _persist_credentials(self) -> None:
        """Persist the store without stalling the loop (kfslint
        async-blocking: this API shares the manager's event loop with
        the router — a slow credentials-volume fsync here would stall
        live inference routing).  Snapshot on the loop (consistent,
        cheap), write in an executor, serialized so an older snapshot
        can never land after a newer one."""
        if not self.credentials_path:
            return
        async with self._persist_lock:
            snapshot = self.credentials.to_dict()
            await asyncio.get_running_loop().run_in_executor(
                None, self.credentials.write_snapshot,
                self.credentials_path, snapshot)

    async def _list_secrets(self, req: Request) -> Response:
        if self.credentials is None:
            return _err("credential store not configured", 404)
        items = [{"name": s.name, "type": s.type,
                  "annotations": s.annotations}
                 for s in self.credentials.secrets.values()]
        return _json({"items": items})

    async def _create_secret(self, req: Request) -> Response:
        if self.credentials is None:
            return _err("credential store not configured", 404)
        try:
            data = self._decode(req)
            secret_type = data["type"]
            if secret_type not in ("s3", "gcs", "azure", "https"):
                raise ValidationError(
                    f"unknown secret type {secret_type!r} "
                    f"(s3 | gcs | azure | https)")
            payload = data.get("data")
            if not isinstance(payload, dict) or not payload:
                raise ValidationError("secret 'data' must be a non-empty "
                                      "JSON object")
            name = self.credentials.add_secret(
                secret_type, payload,
                annotations=data.get("annotations"),
                name=data.get("name"))
            account = data.get("serviceAccount")
            if account:
                self.credentials.attach(account, name)
            await self._persist_credentials()
        except (ValidationError, KeyError, TypeError) as e:
            return _err(str(e), 422)
        return _json({"name": name,
                      "serviceAccount": account or None}, status=201)

    async def _delete_secret(self, req: Request) -> Response:
        if self.credentials is None:
            return _err("credential store not configured", 404)
        name = req.path_params["name"]
        try:
            self.credentials.remove_secret(name)
        except KeyError:
            return _err(f"secret {name} not found", 404)
        await self._persist_credentials()
        return _json({"deleted": name})

    async def _list_service_accounts(self, req: Request) -> Response:
        if self.credentials is None:
            return _err("credential store not configured", 404)
        return _json({"serviceAccounts": {
            k: list(v)
            for k, v in self.credentials.service_accounts.items()}})

    async def _attach_secret(self, req: Request) -> Response:
        if self.credentials is None:
            return _err("credential store not configured", 404)
        account = req.path_params["name"]
        try:
            data = self._decode(req)
            secret = data.get("secret")
            if not isinstance(secret, str) or not secret:
                raise ValidationError("body must carry a 'secret' name")
            self.credentials.attach(account, secret)
        except ValidationError as e:
            return _err(str(e), 422)
        except KeyError as e:
            return _err(str(e), 404)
        await self._persist_credentials()
        return _json({"serviceAccount": account,
                      "secrets": list(
                          self.credentials.service_accounts[account])})
