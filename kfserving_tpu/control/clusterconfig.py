"""Cluster-tier configuration (tier 1 of the three-tier config system).

The reference keeps cluster-wide serving policy in the
``inferenceservice-config`` ConfigMap — per-framework runtime
images/versions, ingress gateways, logger/batcher/agent resource bounds,
and credential file names (reference config/configmap/
inferenceservice.yaml:1-120, parsed at pkg/apis/serving/v1beta1/
configmap.go:121-158 on every reconcile).  The TPU build has no images;
the per-framework entry is the *entrypoint module* the subprocess
orchestrator execs plus default runtime knobs.

Tier 2 is the InferenceService spec (control/spec.py); tier 3 is process
flags (server/app.py parser).  Spec fields override cluster defaults;
flags are per-process only.
"""

import json
import logging
from dataclasses import dataclass, field
from typing import Dict, Optional

logger = logging.getLogger("kfserving_tpu.control.clusterconfig")

# Per-framework runtime registry (reference configmap `predictors` block:
# image/defaultImageVersion/supportedFrameworks per entry).
DEFAULT_PREDICTOR_RUNTIMES = {
    "jax": {
        "module": "kfserving_tpu.predictors.jaxserver",
        "multiModel": True,
        "defaultTimeout": 300,
    },
    "generative": {
        "module": "kfserving_tpu.predictors.llmserver",
        "multiModel": False,
        "defaultTimeout": 300,
    },
    "sklearn": {
        "module": "kfserving_tpu.predictors.sklearnserver",
        "multiModel": False,
        "defaultTimeout": 60,
    },
    "xgboost": {
        "module": "kfserving_tpu.predictors.xgbserver",
        "multiModel": False,
        "defaultTimeout": 60,
    },
    "lightgbm": {
        "module": "kfserving_tpu.predictors.lgbserver",
        "multiModel": False,
        "defaultTimeout": 60,
    },
    "pmml": {
        "module": "kfserving_tpu.predictors.pmmlserver",
        "multiModel": False,
        "defaultTimeout": 60,
    },
    "pytorch": {
        "module": "kfserving_tpu.predictors.torchserver",
        "multiModel": False,
        "defaultTimeout": 60,
    },
    # External server runtimes (reference TFServing/Triton/ONNX images;
    # SURVEY §2.1 "keep all 9").  `command` is the server binary (a
    # deployment concern — point it at the installed binary or a
    # wrapper); `argStyle` picks the runtime's own CLI convention in
    # subprocess_orchestrator._external_command.
    "tensorflow": {
        "command": ["tensorflow_model_server"],
        "argStyle": "tfserving",
        "defaultImageVersion": "1.14.0",
        "defaultTimeout": 60,
    },
    "triton": {
        "command": ["tritonserver"],
        "argStyle": "triton",
        "defaultImageVersion": "20.03-py3",
        "defaultTimeout": 60,
    },
    "onnx": {
        "command": ["onnx_server"],
        "argStyle": "onnx",
        "defaultImageVersion": "v1.0.0",
        "defaultTimeout": 60,
    },
}


@dataclass
class IngressConfig:
    """Reference `ingress` block (gateway + service); here: bind address."""

    host: str = "127.0.0.1"
    port: int = 8080


@dataclass
class LoggerConfig:
    """Payload logger bounds (reference agent_injector.go:64-113 caps the
    sidecar's resources; here the worker pool / queue are the bound)."""

    workers: int = 5
    max_queue: int = 100


@dataclass
class BatcherConfig:
    """Cluster ceilings for per-isvc batcher requests (the reference caps
    the sidecar's memory; the TPU analogue caps compiled-shape count)."""

    max_batch_size_limit: int = 256
    min_latency_ms: float = 0.5


@dataclass
class AutoscalerConfig:
    target_concurrency: float = 4.0
    tick_seconds: float = 2.0
    # Predictive control loop (control/predictive.py): feed-forward
    # sizing off the router's burn rates + standby pre-arming +
    # brownout admission.  Engages only for models with declared SLO
    # objectives (KFS_SLO_*); `predictive: false` restores the pure
    # reactive loop.
    predictive: bool = True
    # Control-plane burn windows (seconds, short -> long) and alert
    # threshold for the fast-burn trigger.
    predictive_windows_s: list = field(
        default_factory=lambda: [10.0, 60.0])
    burn_alert: float = 2.0
    # Brownout exit hysteresis: short-window burn must sit below
    # burn_exit for exit_ticks consecutive ticks per level step-down.
    burn_exit: float = 1.0
    exit_ticks: int = 3
    # Slope-aware gap sizing (ISSUE 17, off by default): inflate the
    # observed service time by the history detector's latency trend
    # slope projected `slope_horizon_s` ahead, so the predictive
    # sizing provisions for where p99 is heading.  Off = the sizing
    # math is exactly the pre-history behavior.
    slope_aware: bool = False
    slope_horizon_s: float = 15.0


@dataclass
class CredentialsConfig:
    """Reference `credentials` block (configmap keys
    gcsCredentialFileName / s3AccessKeyIDName / ...)."""

    gcs_credential_file_name: str = "gcloud-application-credentials.json"
    s3_access_key_id_name: str = "awsAccessKeyID"
    s3_secret_access_key_name: str = "awsSecretAccessKey"
    # Path to the secret-store JSON (storage/credentials.py schema);
    # the single-host analogue of K8s Secret objects.
    store_file: Optional[str] = None


@dataclass
class ClusterConfig:
    predictors: Dict[str, dict] = field(
        default_factory=lambda: {
            k: dict(v) for k, v in DEFAULT_PREDICTOR_RUNTIMES.items()})
    ingress: IngressConfig = field(default_factory=IngressConfig)
    logger: LoggerConfig = field(default_factory=LoggerConfig)
    batcher: BatcherConfig = field(default_factory=BatcherConfig)
    autoscaler: AutoscalerConfig = field(default_factory=AutoscalerConfig)
    credentials: CredentialsConfig = field(
        default_factory=CredentialsConfig)
    # Where TrainedModel shard configs (models.json) are written.
    modelconfig_dir: Optional[str] = None

    @classmethod
    def load(cls, path: Optional[str]) -> "ClusterConfig":
        """Parse a JSON config file; absent path/file -> all defaults
        (the reference reads the ConfigMap on every reconcile; a restart
        picks up changes here)."""
        cfg = cls()
        if not path:
            return cfg
        with open(path) as f:
            data = json.load(f)
        return cls.from_dict(data)

    @classmethod
    def from_dict(cls, data: dict) -> "ClusterConfig":
        cfg = cls()
        for name, entry in (data.get("predictors") or {}).items():
            base = cfg.predictors.setdefault(name, {})
            base.update(entry)
        for key, klass in (("ingress", IngressConfig),
                           ("logger", LoggerConfig),
                           ("batcher", BatcherConfig),
                           ("autoscaler", AutoscalerConfig),
                           ("credentials", CredentialsConfig)):
            if isinstance(data.get(key), dict):
                setattr(cfg, key, klass(**data[key]))
        if data.get("modelconfig_dir"):
            cfg.modelconfig_dir = data["modelconfig_dir"]
        return cfg

    def runtime_for(self, framework: str) -> dict:
        entry = self.predictors.get(framework)
        if entry is None:
            raise KeyError(
                f"no predictor runtime configured for framework "
                f"{framework!r} (configured: {sorted(self.predictors)})")
        return entry
