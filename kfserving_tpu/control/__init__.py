"""Control plane: declarative serving specs reconciled into running
replicas, with routing, canary, autoscaling, and multi-model sharding.

The reference's control plane is a Kubernetes operator (reference
pkg/controller/v1beta1/inferenceservice/controller.go:68-161) that
delegates actuation to Knative/Istio.  The TPU build keeps the same
layering with explicit, swappable backends:

- spec.py:        the InferenceService/TrainedModel schema (reference
                  pkg/apis/serving/v1beta1/) plus TPU-only fields
                  (mesh parallelism, HBM budget, shape buckets).
- defaults.py:    defaulting webhook equivalent.
- validation.py:  validating webhook equivalent.
- modelconfig.py: models.json shard-config codec (reference
                  pkg/modelconfig/configmap.go).
- sharding.py:    HBM-aware bin-packing shard strategy — the reference's
                  always-shard-0 stub made real (reference
                  pkg/controller/v1alpha1/trainedmodel/sharding/memory/
                  strategy.go:29-39).
- reconciler.py:  spec -> desired replica set -> Orchestrator actuation,
                  with revision tracking for canary (reference
                  ksvc_reconciler.go:64-151).
- router.py:      HTTP ingress: transformer->predictor chain,
                  :predict/:explain split, canary weighted routing
                  (reference ingress_reconciler.go:164-236).
- autoscaler.py:  concurrency-based replica autoscaling with
                  scale-to-zero (Knative KPA equivalent).
- rollout.py:     SLO-gated progressive delivery: RolloutPolicy-driven
                  canary stepping with warmup gating, per-revision
                  health gates, and auto-rollback with quarantine
                  (no reference counterpart — its canary split is
                  operator-stepped).
"""

from kfserving_tpu.control.spec import (  # noqa: F401
    BatcherSpec,
    ComponentSpec,
    InferenceService,
    LoggerSpec,
    ParallelismSpec,
    PredictorSpec,
    RolloutPolicy,
    TrainedModel,
)
