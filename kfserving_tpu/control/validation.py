"""Validation: the validating-webhook equivalent.

Reference rules (pkg/apis/serving/v1beta1/
inference_service_validation.go:46-82 + component.go:109-176): DNS-1035
name, exactly one predictor implementation, storage URI prefix whitelist,
replica/concurrency bounds, logger mode enum.  TPU adds mesh-axis and
bucket sanity.
"""

from typing import List

from kfserving_tpu.control.spec import (
    NAME_REGEX,
    PREDICTOR_FRAMEWORKS,
    STORAGE_URI_PREFIXES,
    InferenceService,
    TrainedModel,
)


class ValidationError(ValueError):
    pass


def _rollout_errors(policy) -> List[str]:
    """RolloutPolicy admission checks: the step schedule must be a
    strictly climbing ladder ending at full traffic, and every gate
    threshold must be meaningful (a zero-regression bound would fail
    every canary on noise)."""
    errors: List[str] = []
    steps = list(policy.steps or [])
    if not steps:
        errors.append("steps must be non-empty")
    elif not all(isinstance(s, int) and 0 < s <= 100 for s in steps):
        errors.append(f"steps must be integers in (0, 100], got {steps}")
    elif any(b <= a for a, b in zip(steps, steps[1:])):
        errors.append(f"steps must be strictly increasing, got {steps}")
    elif steps[-1] != 100:
        errors.append(f"steps must end at 100, got {steps}")
    if policy.hold_s < 0:
        errors.append("hold_s must be >= 0")
    if policy.settle_s < 0:
        errors.append("settle_s must be >= 0")
    if not 0.0 <= policy.max_error_ratio <= 1.0:
        errors.append("max_error_ratio must be in [0, 1]")
    if policy.max_latency_regression < 1.0:
        errors.append("max_latency_regression must be >= 1.0")
    if policy.min_requests < 0:
        errors.append("min_requests must be >= 0")
    if policy.warmup_probes < 0:
        errors.append("warmup_probes must be >= 0")
    if policy.warmup_timeout_s < 0:
        errors.append("warmup_timeout_s must be >= 0")
    return errors


def validate(isvc: InferenceService) -> None:
    errors: List[str] = []
    if not NAME_REGEX.match(isvc.name or ""):
        errors.append(
            f"name {isvc.name!r} must match {NAME_REGEX.pattern}")
    pred = isvc.predictor
    if pred.framework not in PREDICTOR_FRAMEWORKS:
        errors.append(
            f"predictor.framework {pred.framework!r} must be one of "
            f"{PREDICTOR_FRAMEWORKS}")
    if pred.framework == "custom":
        if not pred.command:
            errors.append("custom predictor requires command")
    elif not pred.storage_uri and not pred.multi_model:
        errors.append("predictor.storage_uri is required "
                      "(non-multi-model)")
    from kfserving_tpu.control.spec import EXTERNAL_RUNTIME_FRAMEWORKS

    if pred.framework in EXTERNAL_RUNTIME_FRAMEWORKS:
        if not pred.storage_uri:
            errors.append(
                f"{pred.framework} predictor requires storage_uri")
        if pred.framework == "onnx" and pred.storage_uri:
            # Reference rule: .onnx file or a directory
            # (predictor_onnxruntime.go:47-53).
            base = pred.storage_uri.rsplit("/", 1)[-1]
            if "." in base and not base.endswith(".onnx"):
                errors.append(
                    f"onnx storage_uri must point at a .onnx file or "
                    f"a directory, got {pred.storage_uri!r}")
    if pred.storage_uri and not pred.storage_uri.startswith(
            tuple(STORAGE_URI_PREFIXES)):
        errors.append(
            f"storage_uri {pred.storage_uri!r} must start with one of "
            f"{STORAGE_URI_PREFIXES}")
    for cname, comp in isvc.components().items():
        if comp.min_replicas < 0:
            errors.append(f"{cname}.min_replicas must be >= 0")
        if comp.max_replicas < comp.min_replicas:
            errors.append(
                f"{cname}.max_replicas must be >= min_replicas")
        if comp.container_concurrency < 0:
            errors.append(f"{cname}.container_concurrency must be >= 0")
        if comp.canary_traffic_percent is not None and not (
                0 <= comp.canary_traffic_percent <= 100):
            errors.append(
                f"{cname}.canary_traffic_percent must be in [0, 100]")
        if comp.logger is not None and comp.logger.mode not in (
                "all", "request", "response"):
            errors.append(
                f"{cname}.logger.mode must be all|request|response")
        if comp.batcher is not None:
            if comp.batcher.max_batch_size <= 0:
                errors.append(f"{cname}.batcher.max_batch_size must be > 0")
            if comp.batcher.max_latency_ms <= 0:
                errors.append(f"{cname}.batcher.max_latency_ms must be > 0")
        if comp.rollout is not None:
            errors.extend(f"{cname}.rollout.{e}"
                          for e in _rollout_errors(comp.rollout))
    if isvc.explainer is not None:
        # Admission-time type check (the reference's validating webhook
        # catches bad specs at apply, not replica actuation).
        from kfserving_tpu.explainers import (
            ARTIFACT_REQUIRED_TYPES,
            EXPLAINER_TYPES,
        )

        etype = isvc.explainer.explainer_type
        if isvc.explainer.command:
            # An explicit command serves any type (the orchestrator's
            # command-first branch); no in-tree checks apply.
            pass
        elif etype == "custom":
            errors.append("custom explainer requires command")
        elif etype not in EXPLAINER_TYPES:
            errors.append(
                f"explainer.explainer_type {etype!r} must be one of "
                f"{list(EXPLAINER_TYPES)} or 'custom' (with command)")
        elif etype in ARTIFACT_REQUIRED_TYPES and \
                not isvc.explainer.storage_uri:
            errors.append(
                f"{etype} explainer requires storage_uri")
        if isvc.explainer.storage_uri and \
                not isvc.explainer.storage_uri.startswith(
                    tuple(STORAGE_URI_PREFIXES)):
            errors.append(
                f"explainer.storage_uri {isvc.explainer.storage_uri!r} "
                f"must start with one of {STORAGE_URI_PREFIXES}")
    par = pred.parallelism
    if par is not None and (par.dp < 1 or par.tp < 1 or par.sp < 1):
        errors.append("parallelism axes must be >= 1")
    else:
        # The mesh must land on a real slice shape (TPU analogue of the
        # reference's accelerator annotation being resolvable).
        from kfserving_tpu.control.topology import (
            TopologyError,
            select_topology,
        )

        try:
            select_topology(pred, isvc.annotations)
        except TopologyError as e:
            errors.append(str(e))
    if errors:
        raise ValidationError("; ".join(errors))


def validate_trained_model(tm: TrainedModel) -> None:
    errors: List[str] = []
    if not NAME_REGEX.match(tm.name or ""):
        errors.append(f"name {tm.name!r} must match {NAME_REGEX.pattern}")
    if not tm.inference_service:
        errors.append("inference_service is required")
    if not tm.storage_uri.startswith(tuple(STORAGE_URI_PREFIXES)):
        errors.append(
            f"storage_uri {tm.storage_uri!r} must start with one of "
            f"{STORAGE_URI_PREFIXES}")
    if tm.memory_bytes < 0:
        errors.append("memory_bytes must be >= 0")
    if errors:
        raise ValidationError("; ".join(errors))
