"""models.json shard-config codec.

Reference format and delta semantics (pkg/modelconfig/configmap.go:34-51,
79-111): the per-shard config is a JSON list of {modelName, modelSpec};
TrainedModel reconciles apply (added, deleted) deltas and the agent
watcher picks the file up.  File writes are atomic (tmp + rename) to give
the watcher the same torn-read-free guarantee kubelet's ..data symlink
swap provides.
"""

import json
import os
import tempfile
from typing import Dict, Iterable, List, Tuple

from kfserving_tpu.control.spec import TrainedModel


def render(models: Iterable[TrainedModel]) -> List[dict]:
    return [m.to_model_spec() for m in models]


def load_file(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return json.load(f)


def write_file(path: str, entries: List[dict]) -> None:
    """Atomic write: the agent watcher must never observe a torn file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".models-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(entries, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def apply_delta(entries: List[dict],
                added: Iterable[TrainedModel] = (),
                deleted: Iterable[str] = ()) -> List[dict]:
    """Pure delta apply (reference ConfigsDelta.Process,
    configmap.go:79-111): added upserts by modelName, deleted removes."""
    by_name: Dict[str, dict] = {e["modelName"]: e for e in entries}
    for tm in added:
        by_name[tm.name] = tm.to_model_spec()
    for name in deleted:
        by_name.pop(name, None)
    return [by_name[k] for k in sorted(by_name)]


def diff_names(entries: List[dict]) -> Tuple[List[str], Dict[str, dict]]:
    names = [e["modelName"] for e in entries]
    return names, {e["modelName"]: e["modelSpec"] for e in entries}
