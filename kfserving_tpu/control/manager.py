"""ServingManager: the deployable control-plane process.

The reference's manager binary wires schemes, both reconcilers, and the
webhooks into one controller-runtime manager (reference
cmd/manager/main.go:59-186).  The TPU equivalent wires the controller,
ingress router, autoscaler, and control API into one asyncio process:

    python -m kfserving_tpu.control serve \
        --config cluster.json --control-port 8081 --ingress-port 8080 \
        --orchestrator subprocess --apply examples/iris.json

Data-plane traffic enters the ingress router (the Istio VS + activator
role); declarative specs enter the control API (the apiserver role); the
autoscaler ticks in the background (the KPA role); replicas are actuated
in-process or as subprocesses.
"""

import asyncio
import json
import logging
import signal
from typing import List, Optional

from kfserving_tpu.control.api import ControlAPI
from kfserving_tpu.control.autoscaler import Autoscaler
from kfserving_tpu.control.clusterconfig import ClusterConfig
from kfserving_tpu.control.controller import Controller
from kfserving_tpu.control.orchestrator import InProcessOrchestrator
from kfserving_tpu.control.predictive import PredictiveScaler
from kfserving_tpu.control.rollout import RolloutManager
from kfserving_tpu.control.router import IngressRouter
from kfserving_tpu.control.spec import InferenceService
from kfserving_tpu.control.subprocess_orchestrator import (
    SubprocessOrchestrator,
)

logger = logging.getLogger("kfserving_tpu.control.manager")


def _load_spec_file(path: str) -> object:
    with open(path) as f:
        return json.load(f)


class ServingManager:
    def __init__(self, cluster_config: Optional[ClusterConfig] = None,
                 orchestrator: str = "inprocess",
                 control_port: int = 8081,
                 ingress_port: Optional[int] = None,
                 host: Optional[str] = None):
        self.cluster_config = cluster_config or ClusterConfig()
        # Tier precedence: explicit args (tier 3) over the cluster
        # config's ingress block (tier 1).
        if ingress_port is None:
            ingress_port = self.cluster_config.ingress.port
        if host is None:
            host = self.cluster_config.ingress.host
        from kfserving_tpu.storage.credentials import CredentialStore

        credentials = CredentialStore.load(
            self.cluster_config.credentials.store_file,
            gcs_file_name=(
                self.cluster_config.credentials.gcs_credential_file_name))
        if orchestrator == "subprocess":
            self.orchestrator = SubprocessOrchestrator(
                self.cluster_config, host=host, credentials=credentials)
        elif orchestrator == "inprocess":
            self.orchestrator = InProcessOrchestrator(
                credentials=credentials)
        else:
            raise ValueError(
                f"unknown orchestrator backend {orchestrator!r} "
                f"(inprocess | subprocess)")
        self.controller = Controller(
            self.orchestrator,
            modelconfig_dir=self.cluster_config.modelconfig_dir)
        # Predictive SLO control loop (ISSUE 12): brownout admission
        # at the router + feed-forward sizing in the autoscaler.
        # Constructed whenever enabled; it stays dormant until a model
        # declares SLO objectives (KFS_SLO_*).
        scaler_cfg = self.cluster_config.autoscaler
        self.brownout = None
        self.predictive = None
        if scaler_cfg.predictive:
            from kfserving_tpu.reliability import BrownoutController

            self.brownout = BrownoutController()
        self.router = IngressRouter(self.controller,
                                    http_port=ingress_port,
                                    brownout=self.brownout)
        if scaler_cfg.predictive:
            self.predictive = PredictiveScaler(
                self.controller, self.router,
                windows_s=tuple(scaler_cfg.predictive_windows_s),
                burn_alert=scaler_cfg.burn_alert,
                burn_exit=scaler_cfg.burn_exit,
                exit_ticks=scaler_cfg.exit_ticks,
                brownout=self.brownout,
                slope_aware=scaler_cfg.slope_aware,
                slope_horizon_s=scaler_cfg.slope_horizon_s)
        self.autoscaler = Autoscaler(
            self.controller, self.router,
            target_concurrency=scaler_cfg.target_concurrency,
            tick_seconds=scaler_cfg.tick_seconds,
            predictive=self.predictive)
        # Progressive delivery: steps canaries up their RolloutPolicy
        # schedule and auto-rolls back failed revisions (no-op for
        # specs without a rollout policy).
        self.rollouts = RolloutManager(self.controller)
        self.api = ControlAPI(
            self.controller, http_port=control_port,
            credentials=credentials,
            credentials_path=self.cluster_config.credentials.store_file)
        self.host = host

    # -- lifecycle ----------------------------------------------------------
    async def start_async(self) -> None:
        # Router first: it publishes cluster_local_url, which the
        # orchestrator bakes into explainer/transformer replicas as
        # predictor_host.  Starting the control API first would open a
        # window where an apply builds replicas with predictor_host
        # None permanently.
        await self.router.start_async(self.host)
        await self.api.start_async(self.host)
        await self.autoscaler.start()
        await self.rollouts.start()
        logger.info("control API on %s:%d, ingress on %s:%d",
                    self.host, self.api.http_port,
                    self.host, self.router.http_port)

    async def stop_async(self) -> None:
        await self.rollouts.stop()
        await self.autoscaler.stop()
        await self.api.stop_async()
        await self.router.stop_async()
        for name in list(self.controller.specs):
            ns, isvc_name = name.split("/", 1)
            await self.controller.remove(isvc_name, ns)
        shutdown = getattr(self.orchestrator, "shutdown", None)
        if shutdown is not None:
            await shutdown()

    async def apply_files(self, paths: List[str]) -> None:
        """Apply spec files at startup (kubectl-apply-at-boot).

        File reads go through an executor (kfslint async-blocking):
        by the time apply_files runs, start_async has the router and
        API serving on this same loop, so a slow spec volume would
        stall live traffic."""
        loop = asyncio.get_running_loop()
        for path in paths:
            data = await loop.run_in_executor(None, _load_spec_file,
                                              path)
            items = data if isinstance(data, list) else [data]
            for item in items:
                isvc = InferenceService.from_dict(item)
                status = await self.controller.apply(isvc)
                logger.info("applied %s/%s (ready=%s)",
                            isvc.namespace, isvc.name, status.ready)

    def run(self, apply: Optional[List[str]] = None) -> None:
        """Blocking entrypoint with graceful signal-driven shutdown."""
        async def _main():
            await self.start_async()
            if apply:
                await self.apply_files(apply)
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                try:
                    loop.add_signal_handler(sig, stop.set)
                except NotImplementedError:
                    pass
            await stop.wait()
            logger.info("shutting down")
            await self.stop_async()

        asyncio.run(_main())
