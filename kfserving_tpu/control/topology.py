"""TPU slice topology selector — the GKE accelerator injector analogue.

The reference copies the `serving.kubeflow.org/gke-accelerator`
annotation into the pod's nodeSelector when (and only when) a GPU
resource is requested (reference
pkg/webhook/admission/pod/accelerator_injector.go:30-47).  The TPU
equivalent has to do more than label-matching: a replica that wants
`dp*tp*sp` chips must land on a slice whose physical topology actually
provides them, slices only come in fixed shapes per generation, and a
JAX process discovers its slice through environment variables
(TPU_ACCELERATOR_TYPE / TPU_TOPOLOGY), not a node selector.

So the selector is a small solver over the published slice shapes:

    placement = select_topology(predictor_spec, isvc.annotations)

- gate: only chip-owning predictors (framework "jax"/"generative", or "custom" with
  an explicit generation annotation) get a placement — CPU frameworks
  return None, mirroring the reference's "GPU requested" gate;
- the mesh size `parallelism.chips_per_replica` picks the smallest
  slice shape that fits (spare chips are recorded, not hidden);
- annotations override: `tpu.kfserving.dev/generation` selects the
  hardware generation, `tpu.kfserving.dev/topology` forces an exact
  shape (validated against the generation's table).

The reconciler threads the placement into the orchestrator; the
subprocess backend exports `placement.env()` into the replica process
exactly where the reference's injector wrote the nodeSelector.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

ANNOTATION_GENERATION = "tpu.kfserving.dev/generation"
ANNOTATION_TOPOLOGY = "tpu.kfserving.dev/topology"

DEFAULT_GENERATION = "v5e"


class TopologyError(ValueError):
    """No slice shape satisfies the requested mesh/annotations."""


@dataclass(frozen=True)
class SlicePlacement:
    """A resolved slice assignment for one replica."""

    generation: str        # "v5e" | "v4" | "v5p"
    topology: str          # e.g. "2x4" (2D) or "2x2x2" (3D)
    chips: int             # chips the slice provides
    hosts: int             # worker VMs in the slice
    accelerator_type: str  # cloud accelerator name, e.g. "v5litepod-8"
    mesh_chips: int        # chips the replica's mesh actually uses

    @property
    def spare_chips(self) -> int:
        return self.chips - self.mesh_chips

    def env(self) -> Dict[str, str]:
        """Replica process environment (how JAX discovers the slice —
        the TPU analogue of the injected nodeSelector)."""
        return {
            "TPU_ACCELERATOR_TYPE": self.accelerator_type,
            "TPU_TOPOLOGY": self.topology,
            "TPU_CHIPS_PER_REPLICA": str(self.mesh_chips),
            "TPU_WORKER_HOSTS": str(self.hosts),
        }


# Published slice shapes per generation: (topology, chips, hosts).
# v5e slices are 2D; single-host up to 8 chips, multi-host VMs carry 4
# chips each.  v4/v5p are 3D with 4 chips per host.  The accelerator
# name counts chips for v5e (v5litepod-N) and TensorCores (2/chip) for
# v4/v5p (v4-2N).
_V5E: Sequence[Tuple[str, int, int]] = (
    ("1x1", 1, 1), ("2x2", 4, 1), ("2x4", 8, 1), ("4x4", 16, 4),
    ("4x8", 32, 8), ("8x8", 64, 16), ("8x16", 128, 32),
    ("16x16", 256, 64),
)
_3D: Sequence[Tuple[str, int, int]] = (
    ("2x2x1", 4, 1), ("2x2x2", 8, 2), ("2x2x4", 16, 4),
    ("2x4x4", 32, 8), ("4x4x4", 64, 16), ("4x4x8", 128, 32),
    ("4x8x8", 256, 64), ("8x8x8", 512, 128),
)

GENERATIONS: Dict[str, Sequence[Tuple[str, int, int]]] = {
    "v5e": _V5E,
    "v4": _3D,
    "v5p": _3D,
}


def _accelerator_type(generation: str, chips: int) -> str:
    if generation == "v5e":
        return f"v5litepod-{chips}"
    return f"{generation}-{2 * chips}"


def _placement(generation: str, shape: Tuple[str, int, int],
               mesh_chips: int) -> SlicePlacement:
    topology, chips, hosts = shape
    return SlicePlacement(
        generation=generation, topology=topology, chips=chips,
        hosts=hosts, accelerator_type=_accelerator_type(generation, chips),
        mesh_chips=mesh_chips)


def select_topology(predictor_spec,
                    annotations: Optional[Dict[str, str]] = None
                    ) -> Optional[SlicePlacement]:
    """Resolve the slice placement for a predictor component.

    Returns None for components that don't own chips.  Raises
    TopologyError when the mesh cannot be placed or an annotation names
    an unknown generation/topology.
    """
    annotations = annotations or {}
    generation = annotations.get(ANNOTATION_GENERATION)
    framework = getattr(predictor_spec, "framework", None)
    if framework not in ("jax", "generative") and not (
            framework == "custom" and generation):
        return None
    generation = generation or DEFAULT_GENERATION
    shapes = GENERATIONS.get(generation)
    if shapes is None:
        raise TopologyError(
            f"unknown TPU generation {generation!r}; known: "
            f"{sorted(GENERATIONS)}")

    par = getattr(predictor_spec, "parallelism", None)
    mesh_chips = par.chips_per_replica if par is not None else 1

    forced = annotations.get(ANNOTATION_TOPOLOGY)
    if forced:
        for shape in shapes:
            if shape[0] == forced:
                if shape[1] < mesh_chips:
                    raise TopologyError(
                        f"topology {forced} has {shape[1]} chips but the "
                        f"mesh needs {mesh_chips} (dp*tp*sp)")
                return _placement(generation, shape, mesh_chips)
        raise TopologyError(
            f"unknown {generation} topology {forced!r}; known: "
            f"{[s[0] for s in shapes]}")

    for shape in shapes:  # tables are sorted ascending by chips
        if shape[1] >= mesh_chips:
            return _placement(generation, shape, mesh_chips)
    largest = shapes[-1]
    raise TopologyError(
        f"mesh needs {mesh_chips} chips but the largest {generation} "
        f"slice is {largest[0]} ({largest[1]} chips); shard across "
        f"replicas (dp) instead")
