"""Defaulting: the mutating-webhook equivalent.

Reference behavior (pkg/apis/serving/v1beta1/
inference_service_defaults.go:31-74): fill resource defaults and call each
component's Default().  TPU defaults additionally bound the batcher to the
engine's bucket ceiling and align mesh axes with the replica's chip count.
"""

from kfserving_tpu.control.spec import (
    BatcherSpec,
    InferenceService,
    ParallelismSpec,
)

DEFAULT_TIMEOUT_SECONDS = 300
DEFAULT_MAX_BATCH_SIZE = 32
DEFAULT_MAX_LATENCY_MS = 5.0


def apply_defaults(isvc: InferenceService) -> InferenceService:
    """Mutates and returns the isvc with defaults filled."""
    for component in isvc.components().values():
        if component.min_replicas < 0:
            component.min_replicas = 0
        if component.max_replicas < component.min_replicas:
            component.max_replicas = max(component.min_replicas, 1)
        if component.timeout_seconds <= 0:
            component.timeout_seconds = DEFAULT_TIMEOUT_SECONDS
        if component.batcher is not None:
            b = component.batcher
            if b.max_batch_size <= 0:
                b.max_batch_size = DEFAULT_MAX_BATCH_SIZE
            if b.max_latency_ms <= 0:
                b.max_latency_ms = DEFAULT_MAX_LATENCY_MS
        if component.rollout is not None and \
                component.canary_traffic_percent is None:
            # Progressive delivery: the rollout manager owns the split.
            # Start at 0% so a brand-new revision's replicas warm up
            # (ready + warmup probes) before the first step grants any
            # traffic.  On a first-ever apply (no previous revision)
            # the reconciler still routes 100% to the only revision.
            component.canary_traffic_percent = 0
    pred = isvc.predictor
    if pred.parallelism is None:
        pred.parallelism = ParallelismSpec()
    if pred.protocol_version not in ("v1", "v2"):
        pred.protocol_version = "v1"
    if pred.multi_model and pred.batcher is None:
        # MMS predictors batch by default: per-model request streams are
        # sparse, so coalescing is what keeps chips busy.
        pred.batcher = BatcherSpec()
    return isvc
