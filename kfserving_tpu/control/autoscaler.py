"""Concurrency-based replica autoscaling (Knative KPA equivalent).

Reference knobs (pkg/apis/serving/v1beta1/component.go:72-82 +
ksvc_reconciler.go:70-83): min/max replicas and containerConcurrency; the
KPA scales on observed concurrency per replica and supports scale-to-zero
with activator buffering.

This autoscaler samples the router's in-flight gauge each tick, averages
over a sliding window, and converges each component to
ceil(avg_concurrency / target_concurrency), clamped to [min, max].
Scale-to-zero fires after `idle_ticks` windows of zero traffic when
min_replicas == 0 (cold start is then the router's _activate path, which
on TPU includes compile time — the persistent compile cache is what makes
it tolerable, SURVEY.md §5.3).
"""

import asyncio
import logging
import math
from collections import deque
from typing import Dict

logger = logging.getLogger("kfserving_tpu.control.autoscaler")

DEFAULT_TARGET_CONCURRENCY = 4.0
WINDOW_TICKS = 6
IDLE_TICKS_TO_ZERO = 30


class Autoscaler:
    def __init__(self, controller, router,
                 target_concurrency: float = DEFAULT_TARGET_CONCURRENCY,
                 tick_seconds: float = 2.0):
        self.controller = controller
        self.router = router
        self.target_concurrency = target_concurrency
        self.tick_seconds = tick_seconds
        self._windows: Dict[str, deque] = {}
        self._idle: Dict[str, int] = {}
        self._task = None

    async def start(self):
        self._task = asyncio.create_task(self._loop())

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self):
        while True:
            try:
                await self.tick()
            except Exception:
                logger.exception("autoscaler tick failed")
            await asyncio.sleep(self.tick_seconds)

    async def tick(self):
        """One scaling evaluation (callable directly in tests)."""
        for name, isvc in list(self.controller.specs.items()):
            for cname, comp in isvc.components().items():
                await self._scale_component(name, isvc, cname, comp)

    async def _scale_component(self, name, isvc, cname, comp):
        gauge_key = f"router/{isvc.name}/{cname}"
        inflight = self.router.inflight.get(gauge_key, 0)
        window = self._windows.setdefault(
            f"{name}/{cname}", deque(maxlen=WINDOW_TICKS))
        window.append(inflight)
        avg = sum(window) / len(window)
        target = (comp.container_concurrency
                  or self.target_concurrency)
        desired = math.ceil(avg / target) if avg > 0 else 0
        key = f"{name}/{cname}"
        if desired == 0:
            self._idle[key] = self._idle.get(key, 0) + 1
            if comp.min_replicas == 0 and \
                    self._idle[key] >= IDLE_TICKS_TO_ZERO:
                await self.controller.reconciler.scale(isvc, cname, 0)
                return
            desired = max(comp.min_replicas, 0)
            if desired == 0:
                return  # stay as-is until idle threshold
        else:
            self._idle[key] = 0
        current = len(self.controller.reconciler.orchestrator.replicas(
            self.controller.reconciler.component_id(isvc, cname)))
        clamped = max(comp.min_replicas, min(comp.max_replicas, desired))
        if clamped != current and clamped > 0:
            logger.info("scaling %s/%s %d -> %d (avg conc %.1f)",
                        name, cname, current, clamped, avg)
            await self.controller.reconciler.scale(isvc, cname, clamped)
