"""Concurrency-based replica autoscaling (Knative KPA equivalent).

Reference knobs (pkg/apis/serving/v1beta1/component.go:72-82 +
ksvc_reconciler.go:70-83): min/max replicas and containerConcurrency; the
KPA scales on observed concurrency per replica and supports scale-to-zero
with activator buffering.

This autoscaler samples the router's in-flight gauge each tick, averages
over a sliding window, and converges each component to
ceil(avg_concurrency / target_concurrency), clamped to [min, max].
Scale-to-zero fires after `idle_ticks` windows of zero traffic when
min_replicas == 0 (cold start is then the router's _activate path, which
on TPU includes compile time — the persistent compile cache is what makes
it tolerable, SURVEY.md §5.3).

With a `PredictiveScaler` attached (control/predictive.py, ISSUE 12)
each tick additionally runs the feed-forward plan: burn-driven sizing
from the router's latency/arrival series, standby pre-arming, and
brownout entry/exit — the reactive signal then acts as the floor, the
prediction as the leading edge.
"""

import asyncio
import logging
import math
from collections import deque
from typing import Dict, Optional

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.reliability import fault_sites, faults

logger = logging.getLogger("kfserving_tpu.control.autoscaler")

DEFAULT_TARGET_CONCURRENCY = 4.0
WINDOW_TICKS = 6
IDLE_TICKS_TO_ZERO = 30
# Generative scaling target: keep engine slot pools at or below this
# utilization (occupancy + queued prefills vs capacity) — the KPA
# "target concurrency" analogue for slot-structured load.
TARGET_SLOT_UTIL = 0.8
# Consecutive failed ticks before the dead control loop is pinned into
# the supervisor flight recorder (one-off failures just retry).
STALL_TICKS = 3


class Autoscaler:
    def __init__(self, controller, router,
                 target_concurrency: float = DEFAULT_TARGET_CONCURRENCY,
                 tick_seconds: float = 2.0,
                 predictive: Optional[object] = None):
        self.controller = controller
        self.router = router
        self.target_concurrency = target_concurrency
        self.tick_seconds = tick_seconds
        # PredictiveScaler (control/predictive.py) or None (pure
        # reactive — the pre-ISSUE-12 behavior, and the bench's
        # baseline arm).
        self.predictive = predictive
        self._windows: Dict[str, deque] = {}
        self._idle: Dict[str, int] = {}
        self._consecutive_failures = 0
        self._stall_pinned = False
        self._task = None

    async def start(self):
        self._task = asyncio.create_task(self._loop())

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self):
        while True:
            try:
                await self.tick()
            except Exception:
                # Swallowing alone made a dead control loop invisible
                # until the next overload: count every failure and pin
                # evidence once the loop is provably stalled, so
                # /debug/flightrecorder (replica="supervisor") shows
                # it before the capacity gap does.
                logger.exception("autoscaler tick failed")
                self._note_tick_failure()
            else:
                self._consecutive_failures = 0
                self._stall_pinned = False
            await asyncio.sleep(self.tick_seconds)

    def _note_tick_failure(self) -> None:
        obs.autoscaler_tick_failures_total().inc()
        self._consecutive_failures += 1
        if self._consecutive_failures < STALL_TICKS or \
                self._stall_pinned:
            return
        self._stall_pinned = True
        from kfserving_tpu.control.predictive import (
            ensure_flight_recorder,
        )

        recorder = ensure_flight_recorder(
            self.controller.reconciler.orchestrator)
        if recorder is not None:
            recorder.record({
                "kind": "autoscaler_stalled",
                "consecutive_failures": self._consecutive_failures,
                "tick_seconds": self.tick_seconds,
            }, pin="autoscaler_stalled")
        logger.error("autoscaler control loop stalled: %d consecutive "
                     "tick failures", self._consecutive_failures)

    async def tick(self):
        """One scaling evaluation (callable directly in tests).  The
        predictive signal snapshot and the brownout evaluation run
        BEFORE the per-component actuation (and before its fault
        site): a wedged scale() must not keep the brownout gate from
        engaging — that ordering is exactly what the chaos test
        injects `autoscaler.tick` faults to prove."""
        if self.predictive is not None:
            self.predictive.observe()
        for name, isvc in list(self.controller.specs.items()):
            if self.predictive is not None:
                # isvc.name, not the namespaced specs key: objectives
                # and the router's series are keyed by model name.
                self.predictive.evaluate_brownout(isvc.name, isvc)
            for cname, comp in isvc.components().items():
                if faults.configured(fault_sites.AUTOSCALER_TICK):
                    await faults.inject(fault_sites.AUTOSCALER_TICK,
                                        key=f"{name}/{cname}")
                await self._scale_component(name, isvc, cname, comp)

    def _occupancy_desired(self, cid: str) -> int:
        """Generative saturation: replicas needed so engine slot
        occupancy (busy slots + queued prefills) sits at or below
        TARGET_SLOT_UTIL of pool capacity.  Returns 0 for components
        without a generation engine (the request-count signal rules
        alone there).  Reads in-process replica handles; subprocess
        replicas without a handle contribute nothing (their load still
        shows in the router's request gauge)."""
        replicas = self.controller.reconciler.orchestrator.replicas(cid)
        busy = 0
        per_replica_cap = 0
        for r in replicas:
            repo = getattr(getattr(r, "handle", None),
                           "repository", None)
            if repo is None:
                continue
            replica_cap = 0
            for m in repo.get_models():
                eng = getattr(m, "engine", None)
                gauges = getattr(eng, "load_gauges", None)
                if gauges is None:
                    continue
                g = gauges()
                busy += g["active_slots"] + g["pending"]
                replica_cap += g["max_slots"]
            # A replica's capacity is the SUM of its engines' pools (a
            # repository may host several generative models).
            per_replica_cap = max(per_replica_cap, replica_cap)
        if per_replica_cap == 0:
            return 0
        return math.ceil(busy / (TARGET_SLOT_UTIL * per_replica_cap))

    async def _scale_component(self, name, isvc, cname, comp):
        gauge_key = f"router/{isvc.name}/{cname}"
        inflight = self.router.inflight.get(gauge_key, 0)
        cid = self.controller.reconciler.component_id(isvc, cname)
        # A generative replica's true load signal: slot occupancy +
        # pending prefill depth.  Request count alone cannot see a
        # replica saturated by a handful of long-lived streams.
        occupancy_load = self._occupancy_desired(cid)
        window = self._windows.setdefault(
            f"{name}/{cname}", deque(maxlen=WINDOW_TICKS))
        window.append(inflight)
        avg = sum(window) / len(window)
        target = (comp.container_concurrency
                  or self.target_concurrency)
        desired = math.ceil(avg / target) if avg > 0 else 0
        desired = max(desired, occupancy_load)
        # Feed-forward: the predictive plan (burn rate x latency
        # model, chain-joint) leads; the reactive average is the
        # floor.  Pre-arming/evidence happen inside the plan call.
        if self.predictive is not None:
            current = len(
                self.controller.reconciler.orchestrator.replicas(cid))
            desired = max(desired, self.predictive.desired_replicas(
                isvc.name, isvc, cname, comp, cid, current))
        key = f"{name}/{cname}"
        if desired == 0:
            self._idle[key] = self._idle.get(key, 0) + 1
            if comp.min_replicas == 0 and \
                    self._idle[key] >= IDLE_TICKS_TO_ZERO:
                await self.controller.reconciler.scale(isvc, cname, 0)
                return
            desired = max(comp.min_replicas, 0)
            if desired == 0:
                return  # stay as-is until idle threshold
        else:
            self._idle[key] = 0
        current = len(
            self.controller.reconciler.orchestrator.replicas(cid))
        clamped = max(comp.min_replicas, min(comp.max_replicas, desired))
        if clamped != current and clamped > 0:
            logger.info("scaling %s/%s %d -> %d (avg conc %.1f)",
                        name, cname, current, clamped, avg)
            await self.controller.reconciler.scale(isvc, cname, clamped)
