"""Concurrency-based replica autoscaling (Knative KPA equivalent).

Reference knobs (pkg/apis/serving/v1beta1/component.go:72-82 +
ksvc_reconciler.go:70-83): min/max replicas and containerConcurrency; the
KPA scales on observed concurrency per replica and supports scale-to-zero
with activator buffering.

This autoscaler samples the router's in-flight gauge each tick, averages
over a sliding window, and converges each component to
ceil(avg_concurrency / target_concurrency), clamped to [min, max].
Scale-to-zero fires after `idle_ticks` windows of zero traffic when
min_replicas == 0 (cold start is then the router's _activate path, which
on TPU includes compile time — the persistent compile cache is what makes
it tolerable, SURVEY.md §5.3).
"""

import asyncio
import logging
import math
from collections import deque
from typing import Dict

logger = logging.getLogger("kfserving_tpu.control.autoscaler")

DEFAULT_TARGET_CONCURRENCY = 4.0
WINDOW_TICKS = 6
IDLE_TICKS_TO_ZERO = 30
# Generative scaling target: keep engine slot pools at or below this
# utilization (occupancy + queued prefills vs capacity) — the KPA
# "target concurrency" analogue for slot-structured load.
TARGET_SLOT_UTIL = 0.8


class Autoscaler:
    def __init__(self, controller, router,
                 target_concurrency: float = DEFAULT_TARGET_CONCURRENCY,
                 tick_seconds: float = 2.0):
        self.controller = controller
        self.router = router
        self.target_concurrency = target_concurrency
        self.tick_seconds = tick_seconds
        self._windows: Dict[str, deque] = {}
        self._idle: Dict[str, int] = {}
        self._task = None

    async def start(self):
        self._task = asyncio.create_task(self._loop())

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self):
        while True:
            try:
                await self.tick()
            except Exception:
                logger.exception("autoscaler tick failed")
            await asyncio.sleep(self.tick_seconds)

    async def tick(self):
        """One scaling evaluation (callable directly in tests)."""
        for name, isvc in list(self.controller.specs.items()):
            for cname, comp in isvc.components().items():
                await self._scale_component(name, isvc, cname, comp)

    def _occupancy_desired(self, cid: str) -> int:
        """Generative saturation: replicas needed so engine slot
        occupancy (busy slots + queued prefills) sits at or below
        TARGET_SLOT_UTIL of pool capacity.  Returns 0 for components
        without a generation engine (the request-count signal rules
        alone there).  Reads in-process replica handles; subprocess
        replicas without a handle contribute nothing (their load still
        shows in the router's request gauge)."""
        replicas = self.controller.reconciler.orchestrator.replicas(cid)
        busy = 0
        per_replica_cap = 0
        for r in replicas:
            repo = getattr(getattr(r, "handle", None),
                           "repository", None)
            if repo is None:
                continue
            replica_cap = 0
            for m in repo.get_models():
                eng = getattr(m, "engine", None)
                gauges = getattr(eng, "load_gauges", None)
                if gauges is None:
                    continue
                g = gauges()
                busy += g["active_slots"] + g["pending"]
                replica_cap += g["max_slots"]
            # A replica's capacity is the SUM of its engines' pools (a
            # repository may host several generative models).
            per_replica_cap = max(per_replica_cap, replica_cap)
        if per_replica_cap == 0:
            return 0
        return math.ceil(busy / (TARGET_SLOT_UTIL * per_replica_cap))

    async def _scale_component(self, name, isvc, cname, comp):
        gauge_key = f"router/{isvc.name}/{cname}"
        inflight = self.router.inflight.get(gauge_key, 0)
        cid = self.controller.reconciler.component_id(isvc, cname)
        # A generative replica's true load signal: slot occupancy +
        # pending prefill depth.  Request count alone cannot see a
        # replica saturated by a handful of long-lived streams.
        occupancy_load = self._occupancy_desired(cid)
        window = self._windows.setdefault(
            f"{name}/{cname}", deque(maxlen=WINDOW_TICKS))
        window.append(inflight)
        avg = sum(window) / len(window)
        target = (comp.container_concurrency
                  or self.target_concurrency)
        desired = math.ceil(avg / target) if avg > 0 else 0
        desired = max(desired, occupancy_load)
        key = f"{name}/{cname}"
        if desired == 0:
            self._idle[key] = self._idle.get(key, 0) + 1
            if comp.min_replicas == 0 and \
                    self._idle[key] >= IDLE_TICKS_TO_ZERO:
                await self.controller.reconciler.scale(isvc, cname, 0)
                return
            desired = max(comp.min_replicas, 0)
            if desired == 0:
                return  # stay as-is until idle threshold
        else:
            self._idle[key] = 0
        current = len(
            self.controller.reconciler.orchestrator.replicas(cid))
        clamped = max(comp.min_replicas, min(comp.max_replicas, desired))
        if clamped != current and clamped > 0:
            logger.info("scaling %s/%s %d -> %d (avg conc %.1f)",
                        name, cname, current, clamped, avg)
            await self.controller.reconciler.scale(isvc, cname, clamped)
