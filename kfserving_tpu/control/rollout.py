"""SLO-gated progressive rollout: the self-driving canary loop.

The reference's canary is two revisions and a traffic split the
operator edits by hand (ksvc_reconciler.go:84-118); PR 3's SLO engine
computes breach signals nothing consumed.  This manager closes the
loop, per TensorFlow-Serving's version-lifecycle manager
(arXiv:1712.06139) and InferLine's objective-driven control
(arXiv:1812.01776): revision health, not a human, gates traffic.

State machine per component with a `RolloutPolicy` and an active
canary pair (latest revision != previous revision):

    warming      new-revision replicas hold 0% traffic until
                 `/v2/health/ready` answers and `warmup_probes`
                 consecutive probes succeed per replica — a revision
                 that loads but cannot serve never takes a step;
    progressing  canary_traffic_percent climbs `policy.steps`,
                 holding `hold_s` at each step while the analyzer
                 compares the canary's per-revision 5xx ratio and
                 latency p95 (the router's revision-tagged series)
                 against the stable revision's;
    promoted     the final step (100) passed its gate: canary becomes
                 the only revision, the previous one is GC'd;
    rolled_back  a failed gate — or an SLO breach reported by a canary
                 replica — reverted traffic to stable in one
                 reconcile, quarantined the revision's content hash
                 (re-applying the identical spec does not re-roll),
                 and pinned the canary's flight-recorder evidence
                 into the rollout record before teardown.

Records are served at the router's `GET /v2/rollouts`; state rides the
`kfserving_tpu_rollout_*` gauges.
"""

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from kfserving_tpu.observability import REGISTRY
from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.metrics import (
    REVISION_LATENCY_SERIES,
    REVISION_REQUESTS_SERIES,
)

logger = logging.getLogger("kfserving_tpu.control.rollout")

DEFAULT_TICK_S = 1.0
# Finished rollouts kept for GET /v2/rollouts after their component
# moves on (bounded — the endpoint must not grow without limit).
HISTORY_SIZE = 64
# Flight-recorder entries pinned into a rollback record per replica.
EVIDENCE_LIMIT = 20

_PHASE_CODE = {"warming": 0, "progressing": 1, "promoted": 2,
               "rolled_back": 3}


def _series_sample(registry, model: str, revision: str) -> Dict[str, Any]:
    """Cumulative per-(model, revision) sample of the router's
    revision-tagged request series: attempt count, 5xx count, latency
    histogram bucket counts."""
    out: Dict[str, Any] = {"total": 0.0, "errors": 0.0,
                           "buckets": None, "counts": None}
    fam = registry.family(REVISION_REQUESTS_SERIES)
    if fam is not None:
        for labels, child in fam.samples():
            if labels.get("model") != model or \
                    labels.get("revision") != revision:
                continue
            out["total"] += child.value
            try:
                if int(labels.get("status", 0)) >= 500:
                    out["errors"] += child.value
            except ValueError:
                pass
    fam = registry.family(REVISION_LATENCY_SERIES)
    if fam is not None:
        for labels, hist in fam.samples():
            if labels.get("model") != model or \
                    labels.get("revision") != revision:
                continue
            with hist._lock:
                counts = list(hist.counts)
            if out["counts"] is None:
                out["buckets"] = list(hist.buckets)
                out["counts"] = [0.0] * len(counts)
            if len(counts) == len(out["counts"]):
                out["counts"] = [a + b for a, b in
                                 zip(out["counts"], counts)]
    return out


def _delta(cur: Dict[str, Any], base: Dict[str, Any]) -> Dict[str, Any]:
    """Window delta of two cumulative samples (counter resets — a
    registry wipe mid-step — clamp to zero instead of going negative)."""
    out = {"total": max(0.0, cur["total"] - base["total"]),
           "errors": max(0.0, cur["errors"] - base["errors"]),
           "buckets": cur["buckets"], "counts": None}
    if cur["counts"] is not None:
        if base["counts"] is not None and \
                len(base["counts"]) == len(cur["counts"]):
            out["counts"] = [max(0.0, a - b) for a, b in
                             zip(cur["counts"], base["counts"])]
        else:
            out["counts"] = list(cur["counts"])
    return out


def _p95_bucket(sample: Dict[str, Any]) -> Optional[int]:
    """Index of the histogram bucket holding the p95 (None = no
    data; index == len(buckets) = the overflow bucket)."""
    counts = sample.get("counts")
    buckets = sample.get("buckets")
    if not counts or buckets is None:
        return None
    total = sum(counts)
    if total <= 0:
        return None
    need = 0.95 * total
    cumulative = 0.0
    for idx, count in enumerate(counts):
        cumulative += count
        if cumulative >= need:
            return idx
    return len(counts) - 1


def _bucket_bound(sample: Dict[str, Any], idx: int) -> float:
    buckets = sample["buckets"]
    return float(buckets[idx]) if idx < len(buckets) else float("inf")


def _p95_ms(sample: Dict[str, Any]) -> Optional[float]:
    """p95 upper bound from histogram bucket counts (None = no data;
    inf = the p95 sits in the overflow bucket)."""
    idx = _p95_bucket(sample)
    if idx is None:
        return None
    return _bucket_bound(sample, idx)


@dataclass
class RolloutRecord:
    """One rollout's lifecycle (active or finished)."""

    cid: str
    namespace: str
    name: str
    component: str
    revision: str       # the canary under evaluation
    stable: str         # the previous-ready revision rollback targets
    policy: Dict[str, Any]
    phase: str = "warming"
    step_idx: int = -1
    percent: int = 0
    reason: str = ""
    started_ts: float = field(default_factory=time.time)
    updated_ts: float = field(default_factory=time.time)
    events: List[Dict[str, Any]] = field(default_factory=list)
    # Pinned flight-recorder entries captured from the canary's
    # replicas at rollback, before their teardown destroys the rings.
    evidence: List[Dict[str, Any]] = field(default_factory=list)
    # -- non-serialized working state --
    started_mono: float = field(default_factory=time.monotonic)
    step_started_mono: float = 0.0
    settled: bool = False
    warmup: Dict[str, int] = field(default_factory=dict)
    baseline_canary: Optional[Dict[str, Any]] = None
    baseline_stable: Optional[Dict[str, Any]] = None

    def event(self, kind: str, **detail: Any) -> None:
        self.updated_ts = time.time()
        self.events.append({"ts": self.updated_ts, "event": kind,
                            **detail})

    def to_dict(self) -> Dict[str, Any]:
        return {
            "component_id": self.cid,
            "namespace": self.namespace,
            "name": self.name,
            "component": self.component,
            "revision": self.revision,
            "stable_revision": self.stable,
            "policy": self.policy,
            "phase": self.phase,
            "step_index": self.step_idx,
            "percent": self.percent,
            "reason": self.reason,
            "started_ts": self.started_ts,
            "updated_ts": self.updated_ts,
            "events": list(self.events),
            "evidence": list(self.evidence),
        }


class RolloutManager:
    """Ticks the rollout state machine over every InferenceService the
    controller holds.  `probe` and `slo_check` are injectable for
    hardware-free tests; the defaults HTTP-probe the canary replicas
    (ready endpoint / federated SLO health)."""

    def __init__(self, controller, tick_seconds: float = DEFAULT_TICK_S,
                 probe: Optional[Callable] = None,
                 slo_check: Optional[Callable] = None,
                 registry=REGISTRY):
        self.controller = controller
        self.tick_seconds = tick_seconds
        self.registry = registry
        self._probe = probe
        self._slo_check = slo_check
        self.records: Dict[str, RolloutRecord] = {}   # cid -> active
        self.history: deque = deque(maxlen=HISTORY_SIZE)
        self._task: Optional[asyncio.Task] = None
        self._session = None
        # The router (and tests) reach the manager through the
        # controller, like reconciler/status.
        controller.rollout_manager = self

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=2.0),
                connector=aiohttp.TCPConnector(force_close=True))
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.tick()
            except Exception:
                logger.exception("rollout tick failed")
            await asyncio.sleep(self.tick_seconds)

    # -- reporting ---------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        """The GET /v2/rollouts body: active rollouts, recent finished
        ones, and the quarantine ledger."""
        return {
            "active": [r.to_dict() for r in self.records.values()],
            "history": list(self.history),
            "quarantine":
                self.controller.reconciler.quarantine_report(),
        }

    def _export_gauges(self, rec: RolloutRecord) -> None:
        obs.rollout_state().labels(
            component=rec.cid, revision=rec.revision).set(
                _PHASE_CODE.get(rec.phase, -1))
        obs.rollout_step_percent().labels(component=rec.cid).set(
            rec.percent)

    # -- tick --------------------------------------------------------------
    async def tick(self) -> None:
        """One state-machine evaluation over every service (callable
        directly in tests, like Autoscaler.tick)."""
        reconciler = self.controller.reconciler
        seen: set = set()
        for key, isvc in list(self.controller.specs.items()):
            status = reconciler.status.get(key)
            if status is None:
                continue
            for cname, comp in isvc.components().items():
                if comp.rollout is None:
                    continue
                cstatus = status.components.get(cname)
                if cstatus is None:
                    continue
                cid = reconciler.component_id(isvc, cname)
                seen.add(cid)
                await self._tick_component(isvc, cname, comp, cstatus,
                                           cid)
        # Services deleted out from under an active rollout.
        for cid in [c for c in self.records if c not in seen]:
            self._finish(self.records.pop(cid), "superseded",
                         reason="service removed")
        for cid, revs in self.controller.reconciler.quarantine.items():
            obs.rollout_quarantined().labels(component=cid).set(
                len(revs))

    async def _tick_component(self, isvc, cname: str, comp, cstatus,
                              cid: str) -> None:
        latest = cstatus.latest_revision
        stable = cstatus.previous_revision
        active = bool(stable) and stable != latest and \
            comp.canary_traffic_percent is not None
        rec = self.records.get(cid)
        if rec is not None and rec.revision != latest:
            # A newer spec superseded the canary mid-rollout (or a
            # rollback moved latest back to stable).
            if rec.phase in ("warming", "progressing"):
                self._finish(rec, "superseded",
                             reason=f"revision {latest} applied")
            self.records.pop(cid, None)
            rec = None
        if not active:
            return
        if rec is None:
            rec = RolloutRecord(
                cid=cid, namespace=isvc.namespace, name=isvc.name,
                component=cname, revision=latest, stable=stable,
                policy={
                    "steps": list(comp.rollout.steps),
                    "hold_s": comp.rollout.hold_s,
                    "settle_s": comp.rollout.settle_s,
                    "max_error_ratio": comp.rollout.max_error_ratio,
                    "max_latency_regression":
                        comp.rollout.max_latency_regression,
                    "min_requests": comp.rollout.min_requests,
                    "warmup_probes": comp.rollout.warmup_probes,
                    "warmup_timeout_s": comp.rollout.warmup_timeout_s,
                })
            rec.event("started", stable=stable)
            self.records[cid] = rec
            logger.info("rollout started: %s canary=%s stable=%s "
                        "steps=%s", cid, latest, stable,
                        comp.rollout.steps)
        if rec.phase == "warming":
            await self._tick_warming(isvc, cname, comp, cid, rec)
        elif rec.phase == "progressing":
            await self._tick_progressing(isvc, cname, comp, cid, rec)
        self._export_gauges(rec)

    # -- warming -----------------------------------------------------------
    async def _tick_warming(self, isvc, cname: str, comp, cid: str,
                            rec: RolloutRecord) -> None:
        policy = comp.rollout
        if policy.warmup_timeout_s > 0 and \
                time.monotonic() - rec.started_mono > \
                policy.warmup_timeout_s:
            # A revision that never becomes ready is the most common
            # bad-revision symptom; without a deadline it would park
            # the rollout (and its 0%-floor replicas) forever.
            rec.event("gate_failed", reason="warmup_timeout",
                      timeout_s=policy.warmup_timeout_s)
            await self._rollback(isvc, cname, cid, rec,
                                 "warmup_timeout")
            return
        replicas = [r for r in
                    self.controller.reconciler.orchestrator.replicas(cid)
                    if r.revision == rec.revision]
        if not replicas:
            return  # reconciler still actuating
        if policy.warmup_probes > 0:
            all_warm = True
            for r in replicas:
                if rec.warmup.get(r.host, 0) >= policy.warmup_probes:
                    continue
                ok = await self._probe_ready(r.host)
                rec.warmup[r.host] = (rec.warmup.get(r.host, 0) + 1
                                      if ok else 0)
                if rec.warmup[r.host] < policy.warmup_probes:
                    all_warm = False
            if not all_warm:
                return
        rec.event("warmed", replicas=[r.host for r in replicas])
        await self._enter_step(isvc, cname, comp, cid, rec, 0)

    async def _probe_ready(self, host: str) -> bool:
        if self._probe is not None:
            result = self._probe(host)
            if asyncio.iscoroutine(result):
                result = await result
            return bool(result)
        if self._session is None:
            return False
        try:
            async with self._session.get(
                    f"http://{host}/v2/health/ready") as resp:
                return resp.status == 200
        except Exception:
            return False

    # -- progressing -------------------------------------------------------
    async def _enter_step(self, isvc, cname: str, comp, cid: str,
                          rec: RolloutRecord, idx: int) -> None:
        percent = comp.rollout.steps[idx]
        rec.phase = "progressing"
        rec.step_idx = idx
        rec.percent = percent
        rec.step_started_mono = time.monotonic()
        rec.settled = comp.rollout.settle_s <= 0
        rec.baseline_canary = _series_sample(self.registry, isvc.name,
                                             rec.revision)
        rec.baseline_stable = _series_sample(self.registry, isvc.name,
                                             rec.stable)
        comp.canary_traffic_percent = percent
        await self.controller.reconciler.reconcile(isvc)
        rec.event("step", index=idx, percent=percent)
        obs.rollout_transitions_total().labels(
            component=cid, event="step").inc()
        logger.info("rollout %s: canary %s -> %d%%", cid,
                    rec.revision, percent)

    async def _tick_progressing(self, isvc, cname: str, comp, cid: str,
                                rec: RolloutRecord) -> None:
        policy = comp.rollout
        if comp.canary_traffic_percent != rec.percent:
            # An external re-apply of the unchanged spec reset the
            # managed split (defaulting pins it to 0).  Re-assert the
            # current step — otherwise a min_requests gate waits
            # forever on a revision receiving no traffic.
            comp.canary_traffic_percent = rec.percent
            await self.controller.reconciler.reconcile(isvc)
        if not rec.settled:
            # Analysis delay (the Kayenta/Flagger shape): the step's
            # first settle_s seconds are cold-start noise — first
            # requests pay lazy imports / compile and would read as a
            # latency regression against a warmed stable.  Gates see
            # only samples observed after the re-baseline below.
            if time.monotonic() - rec.step_started_mono < \
                    comp.rollout.settle_s:
                return
            rec.settled = True
            rec.baseline_canary = _series_sample(
                self.registry, isvc.name, rec.revision)
            rec.baseline_stable = _series_sample(
                self.registry, isvc.name, rec.stable)
        canary = _delta(
            _series_sample(self.registry, isvc.name, rec.revision),
            rec.baseline_canary or {"total": 0, "errors": 0,
                                    "buckets": None, "counts": None})
        stable = _delta(
            _series_sample(self.registry, isvc.name, rec.stable),
            rec.baseline_stable or {"total": 0, "errors": 0,
                                    "buckets": None, "counts": None})
        failure = self._gate_failure(policy, canary, stable)
        if failure is None and await self._canary_slo_breach(isvc, cid,
                                                             rec):
            failure = ("slo_breach",
                       {"detail": "canary replica reports SLO alert"})
        if failure is not None:
            reason, detail = failure
            rec.event("gate_failed", step=rec.step_idx, reason=reason,
                      **detail)
            await self._rollback(isvc, cname, cid, rec, reason)
            return
        held_s = time.monotonic() - rec.step_started_mono
        if held_s < policy.hold_s or canary["total"] < \
                policy.min_requests:
            return
        rec.event("gate_passed", step=rec.step_idx,
                  canary_requests=canary["total"],
                  canary_errors=canary["errors"])
        if rec.step_idx + 1 < len(policy.steps):
            await self._enter_step(isvc, cname, comp, cid, rec,
                                   rec.step_idx + 1)
        else:
            await self._promote(isvc, cname, comp, cid, rec)

    def _gate_failure(self, policy, canary: Dict, stable: Dict
                      ) -> Optional[tuple]:
        """Evaluate the hard gates on this step's window; None = pass.
        Gates only engage once the canary has enough evidence
        (min_requests, floor 1) — an idle canary cannot fail."""
        need = max(policy.min_requests, 1)
        if canary["total"] < need:
            return None
        canary_err = canary["errors"] / canary["total"]
        stable_err = (stable["errors"] / stable["total"]
                      if stable["total"] > 0 else 0.0)
        if canary_err > stable_err + policy.max_error_ratio:
            return ("error_ratio", {
                "canary_error_ratio": round(canary_err, 4),
                "stable_error_ratio": round(stable_err, 4),
                "max_error_ratio": policy.max_error_ratio})
        canary_idx = _p95_bucket(canary)
        stable_idx = _p95_bucket(stable)
        if canary_idx is not None and stable_idx is not None and \
                stable["total"] >= need:
            canary_p95 = _bucket_bound(canary, canary_idx)
            stable_p95 = _bucket_bound(stable, stable_idx)
            # Bucketed percentiles are quantized by the bucket
            # geometry (~2x here): two ADJACENT buckets can differ by
            # 2x with near-identical underlying latencies, so a ratio
            # policy only engages when the p95s sit more than one
            # bucket apart — claims finer than the measurement's
            # resolution are noise, not regressions (live-fire verify:
            # 5ms-vs-10ms bucket adjacency read as a "2x regression").
            if canary_idx > stable_idx + 1 and \
                    canary_p95 > stable_p95 * \
                    policy.max_latency_regression:
                return ("latency_regression", {
                    "canary_p95_ms": canary_p95,
                    "stable_p95_ms": stable_p95,
                    "max_latency_regression":
                        policy.max_latency_regression})
        return None

    async def _canary_slo_breach(self, isvc, cid: str,
                                 rec: RolloutRecord) -> bool:
        """SLO breach attributed to the canary REVISION: only the
        canary's own replicas are consulted, so a fleet-wide burn
        caused by the stable side never blames the canary."""
        hosts = [r.host for r in
                 self.controller.reconciler.orchestrator.replicas(cid)
                 if r.revision == rec.revision]
        if self._slo_check is not None:
            result = self._slo_check(isvc.name, hosts)
            if asyncio.iscoroutine(result):
                result = await result
            return bool(result)
        if self._session is None or not hosts:
            return False
        for host in hosts:
            try:
                async with self._session.get(
                        f"http://{host}/v2/health/slo") as resp:
                    if resp.status != 200:
                        continue
                    body = await resp.json()
            except Exception:
                continue
            if isvc.name in body.get("alerting", []):
                return True
        return False

    # -- terminal transitions ----------------------------------------------
    async def _promote(self, isvc, cname: str, comp, cid: str,
                       rec: RolloutRecord) -> None:
        comp.canary_traffic_percent = None
        await self.controller.reconciler.promote(isvc, cname)
        rec.percent = 100
        self._finish(rec, "promoted")
        self.records.pop(cid, None)
        obs.rollout_transitions_total().labels(
            component=cid, event="promoted").inc()
        logger.info("rollout %s: canary %s promoted to 100%%", cid,
                    rec.revision)

    async def _rollback(self, isvc, cname: str, cid: str,
                        rec: RolloutRecord, reason: str) -> None:
        # Evidence FIRST: the canary replicas' pinned flight-recorder
        # entries (5xx, deadline sheds, SLO violations auto-pin there)
        # are copied into the record before the rollback reconcile
        # tears those replicas — and their rings — down.
        rec.evidence = await self._collect_evidence(cid, rec)
        quarantined = await self.controller.reconciler.rollback(
            isvc, cname, reason=reason)
        rec.reason = reason
        self._finish(rec, "rolled_back", reason=reason,
                     quarantined=quarantined)
        self.records.pop(cid, None)
        obs.rollout_transitions_total().labels(
            component=cid, event="rolled_back").inc()
        logger.warning("rollout %s: canary %s rolled back (%s), "
                       "%d evidence entries pinned", cid, rec.revision,
                       reason, len(rec.evidence))

    async def _collect_evidence(self, cid: str, rec: RolloutRecord
                                ) -> List[Dict[str, Any]]:
        evidence: List[Dict[str, Any]] = []
        # Supervisor-side evidence first: the orchestrator's pinned
        # failover/swap-failure timelines for this component (a canary
        # that kept crashing shows up HERE — its own ring died with
        # every crash).
        recorder = getattr(self.controller.reconciler.orchestrator,
                           "flight_recorder", None)
        if recorder is not None:
            dump = recorder.dump(limit=EVIDENCE_LIMIT,
                                 pinned_only=True)
            evidence += [dict(e, replica="supervisor")
                         for e in dump.get("pinned", [])
                         if e.get("component") == cid]
        if self._session is None:
            return evidence
        hosts = [r.host for r in
                 self.controller.reconciler.orchestrator.replicas(cid)
                 if r.revision == rec.revision]
        for host in hosts:
            try:
                async with self._session.get(
                        f"http://{host}/debug/flightrecorder"
                        f"?pinned=1&limit={EVIDENCE_LIMIT}") as resp:
                    if resp.status != 200:
                        continue
                    body = await resp.json()
            except Exception:
                continue
            evidence += [dict(e, replica=host)
                         for e in body.get("pinned", [])]
        return evidence

    def _finish(self, rec: RolloutRecord, phase: str,
                **detail: Any) -> None:
        rec.phase = phase
        rec.event(phase, **detail)
        self.history.append(rec.to_dict())
        # Series hygiene: revisions that stopped existing with this
        # transition must not leak registry children forever (a
        # control plane doing rollouts daily would otherwise grow
        # /metrics and every analyzer scan without bound).
        dead = {"promoted": rec.stable,
                "rolled_back": rec.revision,
                "superseded": rec.revision}.get(phase)
        if dead:
            obs.revision_requests_total().prune(model=rec.name,
                                                revision=dead)
            obs.revision_request_ms().prune(model=rec.name,
                                            revision=dead)
        # One rollout_state child per component: drop earlier
        # revisions' children, then export this terminal state.
        obs.rollout_state().prune(component=rec.cid)
        self._export_gauges(rec)
