"""InferenceService / TrainedModel spec schema.

Re-expresses the reference CRD types (reference
pkg/apis/serving/v1beta1/inference_service.go:24-36 — Predictor required,
Transformer/Explainer optional; component extension knobs
component.go:72-95; per-framework one-of predictor.go:33-59) as plain
dataclasses serializable to/from JSON/YAML-shaped dicts.

TPU-first additions, absent in the reference because it never touched
model internals (SURVEY.md §2.3):
- ParallelismSpec (dp/tp/sp mesh axes per replica);
- hbm_budget_bytes on the predictor (multi-model admission);
- batcher.max_latency_ms at millisecond granularity and shape buckets.
"""

import re
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

# Frameworks the predictor one-of accepts (reference predictor.go:33-59
# lists 8 + custom; 'jax' is the TPU-native addition replacing triton/
# tfserving — those artifacts convert offline; 'pytorch' serves the
# reference's pytorchserver contract on the host CPU for migration).
PREDICTOR_FRAMEWORKS = (
    "jax", "generative", "sklearn", "xgboost", "lightgbm", "pmml",
    "pytorch", "tensorflow", "triton", "onnx", "custom")

# Frameworks served by EXTERNAL server binaries (the reference's
# TFServing/Triton/ONNXRuntime container images, predictor.go:33-59):
# the subprocess orchestrator builds their argv per the runtime's own
# CLI convention from the cluster config's command entry
# (predictor_tfserving.go:84-90, predictor_triton.go:59-67,
# predictor_onnxruntime.go:67-72).  The binaries are deployment
# config — not bundled here.
EXTERNAL_RUNTIME_FRAMEWORKS = ("tensorflow", "triton", "onnx")

NAME_REGEX = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")  # k8s DNS-1035
STORAGE_URI_PREFIXES = (
    "gs://", "s3://", "file://", "http://", "https://", "pvc://", "/")


@dataclass
class LoggerSpec:
    """Payload logging (reference inference_service.go:53-64)."""

    url: str = ""
    mode: str = "all"  # all | request | response


@dataclass
class BatcherSpec:
    """Dynamic batching (reference inference_service.go:66-77; TPU adds
    millisecond deadlines — the reference floor was whole seconds)."""

    max_batch_size: int = 32
    max_latency_ms: float = 5.0


@dataclass
class ParallelismSpec:
    """Within-replica mesh (TPU-native; reference has no counterpart)."""

    dp: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def chips_per_replica(self) -> int:
        return self.dp * self.tp * self.sp


@dataclass
class ComponentSpec:
    """Shared component knobs (reference component.go:72-95)."""

    min_replicas: int = 1
    max_replicas: int = 1
    container_concurrency: int = 0  # 0 = unlimited
    timeout_seconds: int = 300
    canary_traffic_percent: Optional[int] = None
    logger: Optional[LoggerSpec] = None
    batcher: Optional[BatcherSpec] = None
    # Credentials are resolved per service account at replica build
    # (reference pod ServiceAccountName + pkg/credentials builder).
    service_account_name: str = "default"


@dataclass
class PredictorSpec(ComponentSpec):
    """Exactly one framework must be set (reference predictor.go:33-59 +
    validation component.go:109-141)."""

    framework: str = "jax"
    storage_uri: str = ""
    runtime_version: str = ""
    protocol_version: str = "v1"
    multi_model: bool = False
    parallelism: ParallelismSpec = field(default_factory=ParallelismSpec)
    hbm_budget_bytes: Optional[int] = None
    # custom framework: explicit command to exec
    command: Optional[List[str]] = None


@dataclass
class TransformerSpec(ComponentSpec):
    command: Optional[List[str]] = None
    storage_uri: str = ""


@dataclass
class ExplainerSpec(ComponentSpec):
    # saliency | anchor_tabular | lime_images | square_attack |
    # fairness | custom (custom needs `command`)
    explainer_type: str = "saliency"
    storage_uri: str = ""
    command: Optional[List[str]] = None


@dataclass
class InferenceService:
    """Top level (reference inference_service.go:24-36)."""

    name: str
    namespace: str = "default"
    predictor: PredictorSpec = field(default_factory=PredictorSpec)
    transformer: Optional[TransformerSpec] = None
    explainer: Optional[ExplainerSpec] = None
    annotations: Dict[str, str] = field(default_factory=dict)
    generation: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "InferenceService":
        d = dict(d)
        pred = d.get("predictor") or {}
        if "parallelism" in pred and isinstance(pred["parallelism"], dict):
            pred["parallelism"] = ParallelismSpec(**pred["parallelism"])
        for key in ("logger", "batcher"):
            if pred.get(key) and isinstance(pred[key], dict):
                pred[key] = (LoggerSpec if key == "logger"
                             else BatcherSpec)(**pred[key])
        d["predictor"] = PredictorSpec(**pred)
        if d.get("transformer") and isinstance(d["transformer"], dict):
            d["transformer"] = TransformerSpec(**_coerce_component(
                d["transformer"]))
        if d.get("explainer") and isinstance(d["explainer"], dict):
            d["explainer"] = ExplainerSpec(**_coerce_component(
                d["explainer"]))
        return cls(**d)

    def components(self) -> Dict[str, ComponentSpec]:
        out: Dict[str, ComponentSpec] = {"predictor": self.predictor}
        if self.transformer is not None:
            out["transformer"] = self.transformer
        if self.explainer is not None:
            out["explainer"] = self.explainer
        return out


def _coerce_component(d: Dict[str, Any]) -> Dict[str, Any]:
    d = dict(d)
    for key in ("logger", "batcher"):
        if d.get(key) and isinstance(d[key], dict):
            d[key] = (LoggerSpec if key == "logger"
                      else BatcherSpec)(**d[key])
    return d


@dataclass
class TrainedModel:
    """Per-model CR for multi-model serving (reference
    pkg/apis/serving/v1alpha1/trained_model.go:49-70)."""

    name: str
    inference_service: str
    storage_uri: str
    framework: str = "jax"
    memory_bytes: int = 0  # declared footprint; feeds sharding + HBM
    namespace: str = "default"

    def to_model_spec(self) -> Dict[str, Any]:
        """models.json entry (reference modelconfig/configmap.go:34-51)."""
        return {
            "modelName": self.name,
            "modelSpec": {
                "storageUri": self.storage_uri,
                "framework": self.framework,
                "memory": self.memory_bytes,
            },
        }
