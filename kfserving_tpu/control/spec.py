"""InferenceService / TrainedModel spec schema.

Re-expresses the reference CRD types (reference
pkg/apis/serving/v1beta1/inference_service.go:24-36 — Predictor required,
Transformer/Explainer optional; component extension knobs
component.go:72-95; per-framework one-of predictor.go:33-59) as plain
dataclasses serializable to/from JSON/YAML-shaped dicts.

TPU-first additions, absent in the reference because it never touched
model internals (SURVEY.md §2.3):
- ParallelismSpec (dp/tp/sp mesh axes per replica);
- hbm_budget_bytes on the predictor (multi-model admission);
- batcher.max_latency_ms at millisecond granularity and shape buckets.
"""

import re
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

# Frameworks the predictor one-of accepts (reference predictor.go:33-59
# lists 8 + custom; 'jax' is the TPU-native addition replacing triton/
# tfserving — those artifacts convert offline; 'pytorch' serves the
# reference's pytorchserver contract on the host CPU for migration).
PREDICTOR_FRAMEWORKS = (
    "jax", "generative", "sklearn", "xgboost", "lightgbm", "pmml",
    "pytorch", "tensorflow", "triton", "onnx", "custom")

# Frameworks served by EXTERNAL server binaries (the reference's
# TFServing/Triton/ONNXRuntime container images, predictor.go:33-59):
# the subprocess orchestrator builds their argv per the runtime's own
# CLI convention from the cluster config's command entry
# (predictor_tfserving.go:84-90, predictor_triton.go:59-67,
# predictor_onnxruntime.go:67-72).  The binaries are deployment
# config — not bundled here.
EXTERNAL_RUNTIME_FRAMEWORKS = ("tensorflow", "triton", "onnx")

NAME_REGEX = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")  # k8s DNS-1035
STORAGE_URI_PREFIXES = (
    "gs://", "s3://", "file://", "http://", "https://", "pvc://", "/")


@dataclass
class LoggerSpec:
    """Payload logging (reference inference_service.go:53-64)."""

    url: str = ""
    mode: str = "all"  # all | request | response


@dataclass
class BatcherSpec:
    """Dynamic batching (reference inference_service.go:66-77; TPU adds
    millisecond deadlines — the reference floor was whole seconds)."""

    max_batch_size: int = 32
    max_latency_ms: float = 5.0


@dataclass
class RolloutPolicy:
    """Self-driving canary schedule (TPU-native; the reference keeps the
    two-revision traffic split but leaves stepping to the operator,
    ksvc_reconciler.go:84-118).  When set, the control plane owns
    `canary_traffic_percent`: a new revision starts at 0% (warmup-gated
    until `/v2/health/ready` plus `warmup_probes` probes pass), then
    climbs `steps`, holding `hold_s` at each while the rollout analyzer
    compares the canary's per-revision error rate and latency
    percentile against the stable revision.  A failed gate rolls
    traffic back to stable in one reconcile and quarantines the
    revision's content hash."""

    steps: List[int] = field(default_factory=lambda: [5, 25, 50, 100])
    hold_s: float = 60.0
    # Analysis delay per step: samples observed in the first settle_s
    # seconds after a traffic change are excluded from the gates — a
    # canary's first requests pay cold-start costs (lazy imports,
    # first-predict compile) that must not read as a latency
    # regression against a warmed stable.
    settle_s: float = 1.0
    # Canary 5xx ratio may exceed stable's by at most this much.
    max_error_ratio: float = 0.02
    # Canary p95 may be at most this multiple of stable p95.
    max_latency_regression: float = 1.5
    # Canary requests observed at a step before its gate can pass
    # (0 = a zero-traffic service still promotes on hold_s alone).
    min_requests: int = 0
    # Consecutive ready-probe successes per replica before first traffic.
    warmup_probes: int = 1
    # A revision that never warms is a failed revision, not a pending
    # one: past this budget the rollout rolls back and quarantines
    # like any other failed gate (0 = wait forever).
    warmup_timeout_s: float = 300.0


@dataclass
class ParallelismSpec:
    """Within-replica mesh (TPU-native; reference has no counterpart)."""

    dp: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def chips_per_replica(self) -> int:
        return self.dp * self.tp * self.sp


@dataclass
class ComponentSpec:
    """Shared component knobs (reference component.go:72-95)."""

    min_replicas: int = 1
    max_replicas: int = 1
    container_concurrency: int = 0  # 0 = unlimited
    timeout_seconds: int = 300
    canary_traffic_percent: Optional[int] = None
    logger: Optional[LoggerSpec] = None
    batcher: Optional[BatcherSpec] = None
    # Progressive delivery: when set, canary_traffic_percent is managed
    # by the rollout state machine (control/rollout.py), not operators.
    rollout: Optional[RolloutPolicy] = None
    # Credentials are resolved per service account at replica build
    # (reference pod ServiceAccountName + pkg/credentials builder).
    service_account_name: str = "default"


@dataclass
class PredictorSpec(ComponentSpec):
    """Exactly one framework must be set (reference predictor.go:33-59 +
    validation component.go:109-141)."""

    framework: str = "jax"
    storage_uri: str = ""
    runtime_version: str = ""
    protocol_version: str = "v1"
    multi_model: bool = False
    parallelism: ParallelismSpec = field(default_factory=ParallelismSpec)
    hbm_budget_bytes: Optional[int] = None
    # custom framework: explicit command to exec
    command: Optional[List[str]] = None


@dataclass
class TransformerSpec(ComponentSpec):
    command: Optional[List[str]] = None
    storage_uri: str = ""


@dataclass
class ExplainerSpec(ComponentSpec):
    # saliency | anchor_tabular | lime_images | square_attack |
    # fairness | custom (custom needs `command`)
    explainer_type: str = "saliency"
    storage_uri: str = ""
    command: Optional[List[str]] = None


@dataclass
class InferenceService:
    """Top level (reference inference_service.go:24-36)."""

    name: str
    namespace: str = "default"
    predictor: PredictorSpec = field(default_factory=PredictorSpec)
    transformer: Optional[TransformerSpec] = None
    explainer: Optional[ExplainerSpec] = None
    annotations: Dict[str, str] = field(default_factory=dict)
    generation: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "InferenceService":
        d = dict(d)
        pred = d.get("predictor") or {}
        if "parallelism" in pred and isinstance(pred["parallelism"], dict):
            pred["parallelism"] = ParallelismSpec(**pred["parallelism"])
        d["predictor"] = PredictorSpec(**_coerce_component(pred))
        if d.get("transformer") and isinstance(d["transformer"], dict):
            d["transformer"] = TransformerSpec(**_coerce_component(
                d["transformer"]))
        if d.get("explainer") and isinstance(d["explainer"], dict):
            d["explainer"] = ExplainerSpec(**_coerce_component(
                d["explainer"]))
        return cls(**d)

    def components(self) -> Dict[str, ComponentSpec]:
        out: Dict[str, ComponentSpec] = {"predictor": self.predictor}
        if self.transformer is not None:
            out["transformer"] = self.transformer
        if self.explainer is not None:
            out["explainer"] = self.explainer
        return out


_COMPONENT_SUBSPECS = {"logger": LoggerSpec, "batcher": BatcherSpec,
                       "rollout": RolloutPolicy}


def _coerce_component(d: Dict[str, Any]) -> Dict[str, Any]:
    d = dict(d)
    for key, cls in _COMPONENT_SUBSPECS.items():
        if d.get(key) and isinstance(d[key], dict):
            d[key] = cls(**d[key])
    return d


@dataclass
class TrainedModel:
    """Per-model CR for multi-model serving (reference
    pkg/apis/serving/v1alpha1/trained_model.go:49-70)."""

    name: str
    inference_service: str
    storage_uri: str
    framework: str = "jax"
    memory_bytes: int = 0  # declared footprint; feeds sharding + HBM
    namespace: str = "default"

    def to_model_spec(self) -> Dict[str, Any]:
        """models.json entry (reference modelconfig/configmap.go:34-51)."""
        return {
            "modelName": self.name,
            "modelSpec": {
                "storageUri": self.storage_uri,
                "framework": self.framework,
                "memory": self.memory_bytes,
            },
        }
