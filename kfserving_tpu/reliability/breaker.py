"""Circuit breaker: closed / open / half-open over a rolling window.

Protects a caller from hammering a dependency that has stopped
answering (the error-storm amplifier: every failed call costs a full
timeout, and a retrying caller multiplies them).  Semantics:

- CLOSED: calls flow; failures are recorded with timestamps.  When
  `failure_threshold` failures land inside the trailing `window_s`,
  the breaker OPENs.
- OPEN: `allow()` is False — callers skip the dependency outright.
  After `reset_timeout_s` the breaker moves to HALF_OPEN.
- HALF_OPEN: up to `half_open_max` trial calls are allowed through.
  A success closes the breaker (window cleared); a failure re-opens
  it and restarts the reset clock.

`half_open_max=0` disables traffic-driven recovery: the breaker stays
open until an external health check calls `reset()` — the router uses
this so trial *requests* never land on a replica that has not first
answered a cheap liveness probe.

Thread-safety: none.  Each breaker belongs to one event loop (the
router's); cross-thread use needs external locking.

Env knobs (`from_env(prefix)`, `KFS_BREAKER_*` fallback):

    {prefix}_BREAKER_THRESHOLD   failures to open (def 5)
    {prefix}_BREAKER_WINDOW_S    rolling window seconds (def 30)
    {prefix}_BREAKER_RESET_S     open -> half-open seconds (def 5)
"""

import logging
import time
from collections import deque
from typing import Callable, Deque

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.reliability.envknobs import env_float

logger = logging.getLogger("kfserving_tpu.reliability.breaker")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Gauge encoding of breaker state (per-replica breaker visibility on
# /metrics: a router scrape shows which hosts rotation is skipping).
_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


def _env_float(name: str, prefix: str, default: float) -> float:
    return env_float(name, prefix, "BREAKER", default)


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 5,
                 window_s: float = 30.0,
                 reset_timeout_s: float = 5.0,
                 half_open_max: int = 1,
                 name: str = "breaker",
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, int(failure_threshold))
        self.window_s = float(window_s)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_max = max(0, int(half_open_max))
        self.name = name
        self._clock = clock
        self._failures: Deque[float] = deque()
        self._state = CLOSED
        self._opened_at = 0.0
        self._half_open_inflight = 0
        self.opened_count = 0  # telemetry

    @classmethod
    def from_env(cls, prefix: str = "KFS", **overrides
                 ) -> "CircuitBreaker":
        params = dict(
            failure_threshold=int(_env_float("THRESHOLD", prefix, 5)),
            window_s=_env_float("WINDOW_S", prefix, 30.0),
            reset_timeout_s=_env_float("RESET_S", prefix, 5.0),
        )
        params.update(overrides)
        return cls(**params)

    # -- state ---------------------------------------------------------------
    @property
    def state(self) -> str:
        self._maybe_half_open()
        return self._state

    def _maybe_half_open(self) -> None:
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.reset_timeout_s:
            self._state = HALF_OPEN
            self._half_open_inflight = 0
            self._export_state()

    def _export_state(self) -> None:
        obs.breaker_state().labels(name=self.name).set(
            _STATE_VALUE[self._state])

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._failures and self._failures[0] < horizon:
            self._failures.popleft()

    # -- caller API ----------------------------------------------------------
    def allow(self) -> bool:
        """May a call proceed right now?  HALF_OPEN admits at most
        `half_open_max` trials until an outcome is recorded."""
        self._maybe_half_open()
        if self._state == CLOSED:
            return True
        if self._state == HALF_OPEN and \
                self._half_open_inflight < self.half_open_max:
            self._half_open_inflight += 1
            return True
        return False

    def record_success(self) -> None:
        if self._state != CLOSED:
            logger.info("breaker %s closed (probe succeeded)",
                        self.name)
            obs.breaker_transitions().labels(
                name=self.name, to=CLOSED).inc()
        self.reset()

    def record_failure(self) -> None:
        now = self._clock()
        if self._state == HALF_OPEN:
            # Trial failed: straight back to open, clock restarted.
            self._trip(now)
            return
        self._failures.append(now)
        self._prune(now)
        if self._state == CLOSED and \
                len(self._failures) >= self.failure_threshold:
            self._trip(now)

    def _trip(self, now: float) -> None:
        if self._state != OPEN:
            self.opened_count += 1
            logger.warning(
                "breaker %s OPEN (%d failures in %.0fs window)",
                self.name, len(self._failures) or 1, self.window_s)
            obs.breaker_transitions().labels(
                name=self.name, to=OPEN).inc()
        self._state = OPEN
        self._opened_at = now
        self._half_open_inflight = 0
        self._export_state()

    def reset(self) -> None:
        """Force-close (external health probe confirmed recovery)."""
        self._state = CLOSED
        self._failures.clear()
        self._half_open_inflight = 0
        self._export_state()
