"""End-to-end reliability substrate: deadlines, retries, breakers, faults.

The reference survives flaky storage, slow models, and overload with
infrastructure the cluster provides for free — queue-proxy timeouts,
sidecar retries, kubelet probes (SURVEY.md §5.3).  A single-host fabric
owns those behaviors itself:

- `Deadline` — a per-request latency budget minted at ingress
  (`x-request-timeout-ms` / gRPC deadline) and carried through the
  stack by contextvar, so every layer (dataplane, batcher queue,
  engine dispatch, decode loop) can shed work that can no longer
  meet its budget instead of wasting device time on it (the
  InferLine per-stage deadline discipline, arxiv 1812.01776).
- `RetryPolicy` — exponential backoff + jitter with retryable-error
  classification, wrapping idempotent I/O edges (artifact downloads,
  model pulls, pre-dispatch client connects — the TensorFlow-Serving
  retried-model-load discipline, arxiv 1712.06139).
- `CircuitBreaker` — closed/open/half-open with a rolling failure
  window; the router keeps one per replica so a sick upstream is
  skipped (and health-reprobed) instead of feeding an error storm.
- `BrownoutController` — selective load shedding for the predicted-
  overload case: per-model brownout levels drop the lowest priority
  tiers first with explicit retriable 503s + Retry-After, and
  deadline-aware admission refuses requests whose remaining budget
  cannot cover the observed service time (control/predictive.py
  drives entry/exit off the SLO burn rates).
- `faults` — the injection harness that keeps the rest honest: tests
  and soak runs inject deterministic error-rate / added-latency /
  hang faults at each wrapped edge (env `KFS_FAULTS` or programmatic).
"""

from kfserving_tpu.reliability.breaker import CircuitBreaker
from kfserving_tpu.reliability.brownout import (
    BrownoutController,
    PRIORITY_HEADER,
    priority_tier,
)
from kfserving_tpu.reliability.deadline import (
    Deadline,
    DeadlineExceeded,
    TIMEOUT_HEADER,
    clear_deadline,
    current_deadline,
    deadline_scope,
)
from kfserving_tpu.reliability.faults import FaultInjected, faults
from kfserving_tpu.reliability.retry import RetryPolicy

__all__ = [
    "BrownoutController", "PRIORITY_HEADER", "priority_tier",
    "CircuitBreaker",
    "Deadline", "DeadlineExceeded", "TIMEOUT_HEADER",
    "clear_deadline", "current_deadline", "deadline_scope",
    "FaultInjected", "faults",
    "RetryPolicy",
]
