"""Brownout admission control: shed selectively instead of melting p99.

When predicted demand exceeds what capacity (replicas + pre-armed
standbys) can physically cover in time, blowing the latency SLO for
EVERY request is the worst outcome: the Knative/queue-proxy analysis
the overload bench reproduced shows an unbounded queue turns a
capacity gap into multi-second p99 for all callers.  Brownout is the
graceful-degradation alternative (the InferLine stance that a latency
objective is a constraint, not a wish): the ingress router sheds the
LOWEST-priority traffic first with explicit retriable 503s +
`Retry-After`, keeping the remaining traffic inside the objective.

Mechanics:

- Requests carry a priority tier in the ``x-kfs-priority`` header
  (``batch`` < ``normal`` < ``critical``; absent/unknown = normal).
- Each model has a brownout *level* set by the predictive control
  loop (control/predictive.py): level 0 admits everything; level N
  sheds tiers below N.  Level 3 sheds even critical traffic — the
  last step before the bounded queues would anyway.
- Deadline-aware queueing: while a brownout is active, a request
  whose remaining budget cannot cover the model's observed service
  time is shed immediately — it would occupy a queue slot (and
  device time) it provably cannot finish in, starving a request that
  could (the "least remaining budget never wastes a slot" rule).
- Every shed is explicit and retriable: 503 + ``Retry-After`` + a
  JSON body carrying ``"retriable": true`` and the active level, so
  clients distinguish load management from failure.

Entry and exit are the predictive controller's calls (it owns the
burn-rate signals); this module owns the level state machine, the
admission verdicts, and the metric families.
"""

import threading
from typing import Dict, Optional, Tuple

from kfserving_tpu.observability import metrics as obs

PRIORITY_HEADER = "x-kfs-priority"
# Tier order: shed lowest first.  Unknown spellings map to normal so
# a typo'd header degrades to the default, never to instant shedding.
PRIORITY_TIERS: Dict[str, int] = {"batch": 0, "normal": 1,
                                  "critical": 2}
DEFAULT_TIER = PRIORITY_TIERS["normal"]
MAX_LEVEL = 3


def priority_tier(value: Optional[str]) -> int:
    if not value:
        return DEFAULT_TIER
    return PRIORITY_TIERS.get(value.strip().lower(), DEFAULT_TIER)


class BrownoutController:
    """Per-model brownout levels + admission verdicts.

    Thread-safe: levels are set from the autoscaler's control loop
    and read on the router's request path."""

    def __init__(self, retry_after_s: float = 1.0):
        self.retry_after_s = retry_after_s
        self._levels: Dict[str, int] = {}
        # Observed mean service time per model (seconds), fed by the
        # predictive controller's latency-series estimate — the
        # "can this request finish inside its budget" yardstick.
        self._service_s: Dict[str, float] = {}
        self._lock = threading.Lock()
        self.sheds = 0

    # -- level state machine ------------------------------------------------
    def level(self, model: str) -> int:
        return self._levels.get(model, 0)

    def active(self) -> bool:
        return any(self._levels.values())

    def set_level(self, model: str, level: int) -> Optional[str]:
        """Move a model to `level` (clamped to [0, MAX_LEVEL]).
        Returns the transition direction (enter|escalate|recover|
        exit) when the level changed, None when it was already
        there."""
        level = max(0, min(MAX_LEVEL, int(level)))
        with self._lock:
            prev = self._levels.get(model, 0)
            if level == prev:
                return None
            if level == 0:
                self._levels.pop(model, None)
            else:
                self._levels[model] = level
        if prev == 0:
            direction = "enter"
        elif level == 0:
            direction = "exit"
        elif level > prev:
            direction = "escalate"
        else:
            direction = "recover"
        obs.brownout_level().labels(model=model).set(float(level))
        obs.brownout_transitions_total().labels(
            model=model, direction=direction).inc()
        return direction

    # -- service-time estimate ----------------------------------------------
    def update_estimate(self, model: str, service_s: float) -> None:
        if service_s > 0:
            self._service_s[model] = service_s

    def service_estimate_s(self, model: str) -> Optional[float]:
        return self._service_s.get(model)

    # -- admission ----------------------------------------------------------
    def admit(self, model: str, tier: int,
              remaining_budget_s: Optional[float] = None
              ) -> Tuple[bool, Optional[str]]:
        """(admitted, shed_reason).  Reasons: ``priority`` (tier below
        the active level) and ``deadline`` (budget cannot cover the
        observed service time while a brownout is active)."""
        level = self._levels.get(model, 0)
        if level <= 0:
            return True, None
        if tier < level:
            self._count_shed(model, "priority")
            return False, "priority"
        service_s = self._service_s.get(model)
        if remaining_budget_s is not None and service_s is not None \
                and remaining_budget_s < service_s:
            self._count_shed(model, "deadline")
            return False, "deadline"
        return True, None

    def _count_shed(self, model: str, reason: str) -> None:
        self.sheds += 1
        obs.brownout_shed_total().labels(model=model,
                                         reason=reason).inc()

    def report(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._levels)
