"""Per-request latency budgets, propagated by contextvar.

A `Deadline` is minted once at the ingress edge (HTTP
`x-request-timeout-ms` header or the gRPC deadline) and rides the
request's context — through handler, dataplane, batcher queue, and
into the engine's worker threads (the engine copies the contextvars
context into its executor).  Every stage that is about to spend
meaningful time on the request calls `raise_if_expired()` first, so
an over-budget request is failed with 504 *before* it consumes a
batch slot or device dispatch, not after.

The budget is wall-clock (`time.monotonic`), not event-loop time:
it must survive executor-thread hops where no loop is running.
"""

import contextlib
import math
import time
from contextvars import ContextVar
from http import HTTPStatus
from typing import Dict, Optional

from kfserving_tpu.protocol.errors import ServingError

TIMEOUT_HEADER = "x-request-timeout-ms"

# Guardrail on client-supplied budgets: a parse of "1e99" must not arm
# a timer in year 10^91, and a sub-millisecond budget is a typo, not a
# latency objective.
MAX_TIMEOUT_MS = 24 * 3600 * 1000.0


class DeadlineExceeded(ServingError):
    """The request's latency budget ran out (maps to HTTP 504 /
    gRPC DEADLINE_EXCEEDED).

    Construction IS the shed event (every path that gives up on a
    request builds one of these, whether it raises or sets it on a
    waiter future), so the per-stage shed counter increments here —
    one central point instead of a counter call at every edge."""

    status_code = HTTPStatus.GATEWAY_TIMEOUT

    def __init__(self, where: str = ""):
        reason = "request deadline exceeded"
        if where:
            reason = f"{reason} ({where})"
        super().__init__(reason)
        try:
            from kfserving_tpu.observability import metrics as obs

            obs.deadline_exceeded_total().labels(
                stage=where or "unknown").inc()
        except Exception:  # telemetry must never mask the 504
            pass


class Deadline:
    """An absolute expiry on the monotonic clock."""

    __slots__ = ("expires_at",)

    def __init__(self, budget_s: float):
        self.expires_at = time.monotonic() + budget_s

    def remaining_s(self) -> float:
        """Seconds left; negative once expired."""
        return self.expires_at - time.monotonic()

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at

    def raise_if_expired(self, where: str = "") -> None:
        if self.expired:
            raise DeadlineExceeded(where)

    @classmethod
    def from_timeout_ms(cls, timeout_ms: float) -> "Deadline":
        return cls(min(float(timeout_ms), MAX_TIMEOUT_MS) / 1000.0)

    @classmethod
    def from_headers(cls, headers: Dict[str, str]
                     ) -> Optional["Deadline"]:
        """Parse the timeout header; absent/garbage/non-positive
        values mean "no deadline" (matching the queue-proxy's
        lenient header handling), never a request failure."""
        raw = headers.get(TIMEOUT_HEADER)
        if not raw:
            return None
        try:
            ms = float(raw)
        except ValueError:
            return None
        # isfinite: float() parses "nan"/"inf", and a NaN budget would
        # poison every downstream comparison (nan <= 0 is False, so a
        # plain positivity check lets it through).
        if not math.isfinite(ms) or ms <= 0:
            return None
        return cls.from_timeout_ms(ms)

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining_s() * 1000:.1f}ms)"


_current: ContextVar[Optional[Deadline]] = ContextVar(
    "kfs_request_deadline", default=None)


def current_deadline() -> Optional[Deadline]:
    """The ambient request deadline, or None when unbudgeted."""
    return _current.get()


def clear_deadline() -> None:
    """Detach the ambient deadline in the CURRENT context.

    Batch-shared work (a flushed dynamic batch serves many requests
    with different budgets) must not inherit whichever single
    request's context happened to trigger the flush — per-request
    budgets are enforced at the queue edge instead."""
    _current.set(None)


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]):
    """Attach `deadline` to the current context for the `with` body.
    None is accepted (no-op scope) so call sites stay unconditional."""
    token = _current.set(deadline)
    try:
        yield deadline
    finally:
        _current.reset(token)


def check_deadline(where: str = "") -> None:
    """Raise DeadlineExceeded if the ambient budget has run out."""
    dl = _current.get()
    if dl is not None and dl.expired:
        raise DeadlineExceeded(where)
