"""Deterministic fault injection at the reliability layer's edges.

Every retry/breaker-wrapped edge calls into the process-global
`faults` injector with a stable site name before doing real work:

    storage.download    Storage.download per-scheme dispatch
    agent.pull          Downloader.download (the agent's model pull)
    client.request      KFServingClient HTTP calls
    router.dispatch     IngressRouter upstream proxy attempts
    dataplane.infer     DataPlane.infer, keyed by model name (inject
                        per-model latency the SLO engine / monitors
                        must detect)
    orchestrator.standby_activate
                        SubprocessOrchestrator standby activation,
                        keyed by "host cid revision:<hash>" — an
                        injected error/hang drives the swap-failure
                        path (incumbent kept serving, broken standby
                        reaped) without breaking a real process

A site with no configuration costs one dict lookup (the common case).
Configuration comes from the `KFS_FAULTS` env var (JSON object keyed
by site) or programmatically (`faults.configure({...})`, tests):

    KFS_FAULTS='{"storage.download": {"error_rate": 0.1, "seed": 7},
                 "router.dispatch":  {"latency_ms": 50, "match": ":9001"}}'

Per-site knobs:

    error_rate   probability of raising FaultInjected (seeded RNG —
                 the sequence of outcomes is deterministic per site)
    fail_first   deterministically fail the first N matching calls
                 (then stop — the retry-then-succeed test shape)
    latency_ms   added delay per call
    hang_s       long sleep per call (simulates a hung dependency;
                 timeout-wrapped edges like the router convert it
                 into the same TimeoutError a real hang produces,
                 so it feeds breakers, not silent stalls)
    match        selector over the call's `key`: whitespace-separated
                 terms that must ALL appear as substrings (e.g. a
                 replica host:port).  Sites embed structured scopes
                 into their keys — the router's dispatch key carries
                 `revision:<hash>`, so `"match": "revision:ab12cd34"`
                 injects canary-only faults that drive the rollout
                 manager's auto-rollback path without hardware
    seed         RNG seed for error_rate draws (default 0)

`FaultInjected` subclasses ConnectionError on purpose: every wrapped
edge already classifies connection-level errors as transient, so an
injected fault exercises exactly the retry/breaker path a real
network flake would.
"""

import asyncio
import json
import logging
import os
import random
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

logger = logging.getLogger("kfserving_tpu.reliability.faults")

ENV_VAR = "KFS_FAULTS"


class FaultInjected(ConnectionError):
    """An injected failure (classified transient by retry policies)."""

    def __init__(self, site: str, key: str = ""):
        detail = f" ({key})" if key else ""
        super().__init__(f"injected fault at {site}{detail}")
        self.site = site


@dataclass
class FaultSpec:
    error_rate: float = 0.0
    fail_first: int = 0
    latency_ms: float = 0.0
    hang_s: float = 0.0
    match: str = ""
    seed: int = 0
    # Per-spec mutable state.
    calls: int = 0
    injected: int = 0
    rng: random.Random = field(default_factory=random.Random,
                               repr=False)

    def __post_init__(self):
        self.rng = random.Random(self.seed)


class FaultInjector:
    """Process-global registry of per-site fault specs."""

    def __init__(self):
        self._sites: Dict[str, FaultSpec] = {}
        self._env_loaded = False

    # Config-surface knobs (name -> coercion); the dataclass's
    # bookkeeping fields (calls/injected/rng) are NOT settable —
    # accepting them would silently disable fail_first counting.
    # Values coerce at CONFIG time: a JSON string "0.5" from KFS_FAULTS
    # must fail here, not as a TypeError inside the serving path.
    _KNOBS = {"error_rate": float, "fail_first": int,
              "latency_ms": float, "hang_s": float,
              "match": str, "seed": int}

    # -- configuration -------------------------------------------------------
    def configure(self, config: Dict[str, Dict]) -> None:
        """Install per-site specs (replaces those sites; other sites
        keep their existing spec).  Unknown keys are rejected loudly —
        a typo'd knob must not silently disable a chaos test — and
        validation is all-or-nothing: a bad spec for one site installs
        NOTHING (a half-applied fault config is the worst kind of
        lie)."""
        specs = {}
        for site, raw in config.items():
            unknown = set(raw) - set(self._KNOBS)
            if unknown:
                raise TypeError(
                    f"unknown fault knob(s) {sorted(unknown)} for "
                    f"site {site!r} (valid: {sorted(self._KNOBS)})")
            coerced = {}
            for knob, value in raw.items():
                try:
                    coerced[knob] = self._KNOBS[knob](value)
                except (TypeError, ValueError):
                    raise TypeError(
                        f"fault knob {knob}={value!r} for site "
                        f"{site!r} is not "
                        f"{self._KNOBS[knob].__name__}-coercible")
            specs[site] = FaultSpec(**coerced)
        self._sites.update(specs)
        self._env_loaded = True  # explicit config wins over env

    def reset(self) -> None:
        """Drop all fault specs (tests call this in teardown)."""
        self._sites.clear()
        self._env_loaded = False

    def _load_env(self) -> None:
        if self._env_loaded:
            return
        self._env_loaded = True
        raw = os.environ.get(ENV_VAR)
        if not raw:
            return
        try:
            config = json.loads(raw)
        except ValueError:
            logger.error("malformed %s (not JSON); no faults active",
                         ENV_VAR)
            return
        try:
            self.configure(config)
        except TypeError as e:
            logger.error("bad fault spec in %s: %s", ENV_VAR, e)
        else:
            logger.warning("fault injection ACTIVE at sites: %s",
                           ", ".join(sorted(config)))

    def configured(self, site: str) -> bool:
        """Cheap hot-path guard: is any spec installed for `site`?
        Lets latency-critical callers skip wrapper machinery (e.g. a
        wait_for envelope) in the no-faults production case."""
        self._load_env()
        return site in self._sites

    def _spec(self, site: str, key: str) -> Optional[FaultSpec]:
        self._load_env()
        spec = self._sites.get(site)
        if spec is None:
            return None
        # Every whitespace-separated term must match (conjunction):
        # "revision:ab12 :9001" scopes a fault to one revision ON one
        # replica.  A single term without spaces behaves exactly as
        # the original substring match.
        if spec.match and any(term not in key
                              for term in spec.match.split()):
            return None
        return spec

    def stats(self) -> Dict[str, Dict[str, int]]:
        return {site: {"calls": s.calls, "injected": s.injected}
                for site, s in self._sites.items()}

    # -- injection -----------------------------------------------------------
    def _decide(self, spec: FaultSpec, site: str, key: str
                ) -> Optional[FaultInjected]:
        spec.calls += 1
        if spec.fail_first and spec.calls <= spec.fail_first:
            spec.injected += 1
            return FaultInjected(site, key)
        if spec.error_rate > 0 and spec.rng.random() < spec.error_rate:
            spec.injected += 1
            return FaultInjected(site, key)
        return None

    def inject_sync(self, site: str, key: str = "") -> None:
        """Executor-thread edges (storage): blocking sleeps."""
        spec = self._spec(site, key)
        if spec is None:
            return
        if spec.latency_ms:
            time.sleep(spec.latency_ms / 1000.0)
        if spec.hang_s:
            time.sleep(spec.hang_s)
        err = self._decide(spec, site, key)
        if err is not None:
            raise err

    async def inject(self, site: str, key: str = "") -> None:
        """Event-loop edges (client, router): async sleeps."""
        spec = self._spec(site, key)
        if spec is None:
            return
        if spec.latency_ms:
            await asyncio.sleep(spec.latency_ms / 1000.0)
        if spec.hang_s:
            await asyncio.sleep(spec.hang_s)
        err = self._decide(spec, site, key)
        if err is not None:
            raise err


faults = FaultInjector()
