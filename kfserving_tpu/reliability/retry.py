"""Exponential-backoff retry with jitter and error classification.

One policy object serves both worlds: `call()` for synchronous edges
(storage downloads run in executor threads) and `acall()` for asyncio
edges (the agent puller, the SDK client).  Retries respect the ambient
request `Deadline`: once the budget is gone, the policy re-raises
instead of sleeping toward a response nobody can use.

Classification is allowlist-based: only errors in `retry_on` are
retried (default: connection-level `OSError`s — the "request never
dispatched / transfer torn" family, which is safe to replay against
idempotent edges).  Everything else (bad config, missing SDK, 4xx
semantics surfaced as RuntimeError/ValueError) fails fast.

Env knobs (`from_env(prefix)`, falling back to the bare `KFS_RETRY_*`
family so one setting tunes every edge):

    {prefix}_RETRY_MAX_ATTEMPTS   total attempts, 1 = no retry (def 3)
    {prefix}_RETRY_BASE_MS        first backoff delay (def 50)
    {prefix}_RETRY_MAX_MS         backoff ceiling (def 2000)
    {prefix}_RETRY_JITTER         +/- fraction of each delay (def 0.2)
"""

import asyncio
import logging
import random
import time
import urllib.error
from typing import Any, Callable, Iterator, Optional, Tuple, Type

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.reliability.deadline import current_deadline
from kfserving_tpu.reliability.envknobs import env_float

logger = logging.getLogger("kfserving_tpu.reliability.retry")

DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (OSError,)
# OSError subclasses that are the environment's FINAL answer, not a
# transient wire condition — replaying a missing path or a permission
# wall can never succeed.
DEFAULT_NEVER_RETRY: Tuple[Type[BaseException], ...] = (
    FileNotFoundError, PermissionError, IsADirectoryError,
    NotADirectoryError)


def _env_float(name: str, prefix: str, default: float) -> float:
    return env_float(name, prefix, "RETRY", default)


class RetryPolicy:
    """attempts, delays, and the transient-vs-terminal judgment."""

    def __init__(self, max_attempts: int = 3,
                 base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0,
                 multiplier: float = 2.0,
                 jitter: float = 0.2,
                 retry_on: Tuple[Type[BaseException], ...]
                 = DEFAULT_RETRY_ON,
                 rng: Optional[random.Random] = None,
                 name: str = "retry"):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = max(0.0, float(base_delay_s))
        self.max_delay_s = max(self.base_delay_s, float(max_delay_s))
        self.multiplier = max(1.0, float(multiplier))
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self.retry_on = retry_on
        self._rng = rng or random.Random()
        self.name = name
        self.retries = 0  # telemetry: total retries performed

    @classmethod
    def from_env(cls, prefix: str = "KFS",
                 default_max_attempts: int = 3,
                 **overrides) -> "RetryPolicy":
        """`default_max_attempts` is the value used when NO env knob
        is set (edges with nested retries pick a smaller one);
        `overrides` win over env unconditionally."""
        params = dict(
            max_attempts=int(_env_float("MAX_ATTEMPTS", prefix,
                                        default_max_attempts)),
            base_delay_s=_env_float("BASE_MS", prefix, 50.0) / 1000.0,
            max_delay_s=_env_float("MAX_MS", prefix, 2000.0) / 1000.0,
            jitter=_env_float("JITTER", prefix, 0.2),
            name=prefix.lower(),
        )
        params.update(overrides)
        return cls(**params)

    def classify(self, exc: BaseException) -> bool:
        """True when `exc` is transient and the call may be replayed.
        Cancellation is never swallowed, and permanent OSError
        subclasses (missing path, permission wall) never replay.
        urllib's HTTPError also subclasses OSError but carries the
        server's verdict: a 4xx is permanent (re-downloading a 404
        three times — nested under the puller's own retry, nine
        times — helps nobody); 5xx stays retryable."""
        if isinstance(exc, asyncio.CancelledError):
            return False
        if isinstance(exc, DEFAULT_NEVER_RETRY):
            return False
        if isinstance(exc, urllib.error.HTTPError):
            return exc.code >= 500 and isinstance(exc, self.retry_on)
        return isinstance(exc, self.retry_on)

    def delays_s(self) -> Iterator[float]:
        """Backoff delay before attempt i+2 (max_attempts-1 values)."""
        delay = self.base_delay_s
        for _ in range(self.max_attempts - 1):
            jittered = delay
            if self.jitter:
                jittered *= 1.0 + self.jitter * self._rng.uniform(-1, 1)
            yield max(0.0, jittered)
            delay = min(delay * self.multiplier, self.max_delay_s)

    def _give_up(self, exc: BaseException, attempt: int) -> bool:
        if not self.classify(exc):
            return True
        dl = current_deadline()
        if dl is not None and dl.expired:
            logger.warning("%s: attempt %d failed and the request "
                           "deadline is spent; not retrying: %s",
                           self.name, attempt, exc)
            return True
        return False

    def _next_delay(self, delays: Iterator[float]) -> Optional[float]:
        """The next backoff delay, or None when sleeping it would
        outlive the ambient budget — the docstring's promise that a
        retry never sleeps toward a response nobody can use."""
        delay = next(delays)
        dl = current_deadline()
        if dl is not None and dl.remaining_s() <= delay:
            return None
        return delay

    def call(self, fn: Callable[..., Any], *args, **kwargs) -> Any:
        """Synchronous retry loop (blocking sleeps — executor-thread
        edges only, never the event loop)."""
        delays = self.delays_s()
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                delay = None
                if attempt < self.max_attempts and \
                        not self._give_up(e, attempt):
                    delay = self._next_delay(delays)
                if delay is None:
                    raise
                logger.warning(
                    "%s: attempt %d/%d failed (%s: %s); retrying "
                    "in %.0fms", self.name, attempt, self.max_attempts,
                    type(e).__name__, e, delay * 1000)
                self.retries += 1
                obs.retry_total().labels(
                    edge=self.name, reason=type(e).__name__).inc()
                time.sleep(delay)

    async def acall(self, fn: Callable[..., Any], *args, **kwargs
                    ) -> Any:
        """Async retry loop (`fn` returns an awaitable; sleeps yield
        the event loop)."""
        delays = self.delays_s()
        for attempt in range(1, self.max_attempts + 1):
            try:
                return await fn(*args, **kwargs)
            except BaseException as e:
                delay = None
                if attempt < self.max_attempts and \
                        not self._give_up(e, attempt):
                    delay = self._next_delay(delays)
                if delay is None:
                    raise
                logger.warning(
                    "%s: attempt %d/%d failed (%s: %s); retrying "
                    "in %.0fms", self.name, attempt, self.max_attempts,
                    type(e).__name__, e, delay * 1000)
                self.retries += 1
                obs.retry_total().labels(
                    edge=self.name, reason=type(e).__name__).inc()
                await asyncio.sleep(delay)
