"""Canonical fault-injection site manifest — GENERATED, do not hand
edit the constants section.

`SITES` is the single source of truth for every site name the
process-global `faults` injector can be called with.  To add a site:
add its row to `SITES`, regenerate the constants with

    python -m kfserving_tpu.tools.analyzers --write-fault-sites

and use the generated constant at the call site
(`faults.inject(fault_sites.ROUTER_DISPATCH, ...)`).  kfslint's
`fault-site` rule enforces both directions in the fast tier: an
inject call whose site is not in this manifest fails the lint (a
typo'd site string can no longer silently never fire), and a manifest
row no inject call uses fails as dead (so this file can't rot into a
list of sites that no longer exist).
"""

from typing import Dict

# {CONSTANT_NAME: (site string, what the site gates)}
SITES: Dict[str, tuple] = {
    "STORAGE_DOWNLOAD": (
        "storage.download",
        "Storage.download per-scheme dispatch"),
    "AGENT_PULL": (
        "agent.pull",
        "Downloader.download (the agent's model pull)"),
    "CLIENT_REQUEST": (
        "client.request",
        "KFServingClient HTTP calls"),
    "ROUTER_DISPATCH": (
        "router.dispatch",
        "IngressRouter upstream proxy attempts (key carries "
        "`revision:<hash>` for canary-scoped chaos)"),
    "DATAPLANE_INFER": (
        "dataplane.infer",
        "DataPlane.infer, keyed by model name (per-model latency "
        "the SLO engine / monitors must detect)"),
    "ORCHESTRATOR_STANDBY_ACTIVATE": (
        "orchestrator.standby_activate",
        "SubprocessOrchestrator standby activation, keyed by `host "
        "cid revision:<hash>` — drives the swap-failure path"),
    "AUTOSCALER_TICK": (
        "autoscaler.tick",
        "Autoscaler per-component scaling evaluation, keyed by "
        "`<isvc>/<component>` — injected delay/failure wedges the "
        "control loop itself (the brownout path must still engage)"),
    "ROUTER_ADMISSION": (
        "router.admission",
        "IngressRouter brownout admission gate, keyed by `<model> "
        "priority:<tier>` — injected faults shed as explicit "
        "retriable 503s, delay stalls admission"),
    "GENERATOR_PREFIX_LOOKUP": (
        "generator.prefix_lookup",
        "GenerationEngine prompt-block prefix-index probe, keyed by "
        "engine name — an injected error forces the whole plan to "
        "MISS (cache-miss storm on demand), proving the lookup "
        "telemetry counts it"),
    "ENGINE_RESIDENCY_SWAP": (
        "engine.residency_swap",
        "ResidencyManager fault-in, keyed by `<model> "
        "source:<warm|cold>` — an injected error fails the swap "
        "BEFORE the admission plan runs, proving a failed fault-in "
        "keeps the incumbent resident set serving (no half-loaded "
        "model ever serves)"),
    "ROUTER_AFFINITY_PICK": (
        "router.affinity_pick",
        "IngressRouter model-affinity ring pick, keyed by `<model> "
        "<component>` — an injected error drops the request to "
        "plain round-robin (counted as outcome=fallback), the "
        "blind-spray escape hatch chaos must prove"),
    "ENGINE_KV_SPILL": (
        "engine.kv_spill",
        "GenerationEngine host-tier spill of capacity-evicted KV "
        "blocks, keyed by engine name — an injected error fails the "
        "spill BEFORE the tier index publishes, proving the "
        "eviction degrades to the drop-on-evict baseline (counted "
        "as cause=capacity_dropped) with bit-exact generation"),
    "ENGINE_KV_FAULTBACK": (
        "engine.kv_faultback",
        "GenerationEngine host-tier fault-back of a returning "
        "turn's spilled blocks, keyed by engine name — an injected "
        "error fails the read BEFORE any pool insert dispatches, "
        "proving the admission plan rolls back and the turn falls "
        "through to a normal re-prefill with bit-exact generation"),
    "ENGINE_KV_EXPORT": (
        "engine.kv_export",
        "GenerationEngine drain-parachute export of live-slot and "
        "hot prefix-chain KV into the durable host tier, keyed by "
        "engine name — an injected error fails the export BEFORE "
        "any tier write, proving the drain degrades to the no- "
        "handoff baseline (every candidate counted outcome=failed) "
        "and the returning conversation re-prefills bit-exact"),
    "ENGINE_KV_IMPORT": (
        "engine.kv_import",
        "GenerationEngine admission of peer-transferred KV payloads "
        "(the /kv/reattach pull path), keyed by engine name — an "
        "injected error rejects the batch BEFORE any tier "
        "publication, proving a failed import leaves the tier "
        "untouched and the turn degrades to a clean re-prefill with "
        "bit-exact output"),
    "ENGINE_SPEC_DRAFT": (
        "engine.spec_draft",
        "GenerationEngine draft-proposal seam of a speculative "
        "decode wave, keyed by engine name — an injected error "
        "degrades THAT wave to plain non-speculative decode with "
        "bit-exact output parity (counted "
        "specdec_fallbacks_total{site=draft}); speculation resumes "
        "when the fault clears"),
    "ENGINE_SPEC_VERIFY": (
        "engine.spec_verify",
        "GenerationEngine K+1-position verify seam of a speculative "
        "decode wave, keyed by engine name — an injected error "
        "degrades THAT wave to plain non-speculative decode with "
        "bit-exact output parity (counted "
        "specdec_fallbacks_total{site=verify})"),
    "OBSERVABILITY_HISTORY_TICK": (
        "observability.history_tick",
        "HistorySampler background tick (probed via the async hook "
        "the server injects) — an injected hang parks only the "
        "sampler task and an injected error is swallowed and "
        "counted, proving history degrades to stale-but-served and "
        "the serving path never blocks on its own telemetry"),
    "OBSERVABILITY_INCIDENT_OPEN": (
        "observability.incident_open",
        "IncidentManager diagnosis worker, probed before each "
        "queued trigger is processed — an injected error is "
        "swallowed and counted "
        "(kfserving_tpu_incident_failures_total), an injected hang "
        "parks only the worker task, proving a wedged incident "
        "pipeline degrades to plain detector pins and predicts "
        "never block on diagnosis"),
}


def site_values() -> Dict[str, str]:
    """{CONSTANT_NAME: site string} view of the manifest."""
    return {name: row[0] for name, row in SITES.items()}


# -- generated constants (python -m kfserving_tpu.tools.analyzers
#    --write-fault-sites) — do not edit below this line -----------------
STORAGE_DOWNLOAD = "storage.download"
AGENT_PULL = "agent.pull"
CLIENT_REQUEST = "client.request"
ROUTER_DISPATCH = "router.dispatch"
DATAPLANE_INFER = "dataplane.infer"
ORCHESTRATOR_STANDBY_ACTIVATE = "orchestrator.standby_activate"
AUTOSCALER_TICK = "autoscaler.tick"
ROUTER_ADMISSION = "router.admission"
GENERATOR_PREFIX_LOOKUP = "generator.prefix_lookup"
ENGINE_RESIDENCY_SWAP = "engine.residency_swap"
ROUTER_AFFINITY_PICK = "router.affinity_pick"
ENGINE_KV_SPILL = "engine.kv_spill"
ENGINE_KV_FAULTBACK = "engine.kv_faultback"
ENGINE_KV_EXPORT = "engine.kv_export"
ENGINE_KV_IMPORT = "engine.kv_import"
ENGINE_SPEC_DRAFT = "engine.spec_draft"
ENGINE_SPEC_VERIFY = "engine.spec_verify"
OBSERVABILITY_HISTORY_TICK = "observability.history_tick"
OBSERVABILITY_INCIDENT_OPEN = "observability.incident_open"
