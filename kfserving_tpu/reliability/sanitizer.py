"""Runtime device-discipline sanitizer (``KFS_SANITIZE=1``).

The static device tier (kfslint's ``host-sync`` /
``jit-recompile-hazard`` rules) proves the *code* can't express the
two silent MFU killers; this module proves the *process* doesn't
commit them at runtime — the dynamic twin, for the paths static
analysis can't see (dynamic dispatch, third-party callbacks, shapes
computed at runtime):

- **transfer guard** — while a generation scheduler loop runs,
  ``jax.transfer_guard("disallow")`` is armed on the loop thread
  (`loop_guard`).  Any implicit host<->device transfer inside a
  decode wave raises, is counted as a ``forbidden_transfer``
  violation, pinned into the flight recorder, and re-raised (a
  sanitize run fails loudly, never quietly).  The sanctioned fetch
  points (`_fetch_wave`, the engine's result fetch) wrap themselves
  in `sanctioned_fetch()` — an explicit ``transfer_guard("allow")``
  scope — mirroring their static ``host-sync`` pragmas.
- **recompile-after-warmup** — engines report every
  first-dispatch-per-shape through
  ``engine/compile_cache.note_compilation``.  Once a source declares
  its warmup complete (`declare_warmup_complete`), any further
  compilation from that source is a ``recompile`` violation: the
  bucket grid was supposed to be closed, and a post-warmup compile is
  a recompile storm's first drop.
- **event-loop stall watchdog** — a heartbeat thread posts
  ``call_soon_threadsafe`` ticks at the configured loop; a tick the
  loop fails to run within ``KFS_SANITIZE_STALL_MS`` (default 250)
  is a ``loop_stall`` violation with the observed stall attached.

Violations land in ``kfserving_tpu_sanitizer_violations_total{kind}``
and, when a flight recorder is attached (the server wires its
monitoring recorder in), as pinned ``sanitizer_<kind>`` entries —
evidence that survives the healthy traffic after the incident.

``KFS_SANITIZE`` unset/0 is a true no-op: every hook degrades to a
dict lookup or a null context manager, jax is never imported from
here, and no thread starts.
"""

import contextlib
import os
import threading
import time
from typing import Any, Dict, Optional

ENV_VAR = "KFS_SANITIZE"
STALL_ENV_VAR = "KFS_SANITIZE_STALL_MS"
DEFAULT_STALL_MS = 250.0

VIOLATION_KINDS = ("forbidden_transfer", "recompile", "loop_stall")


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") not in ("", "0", "false")


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.violations: Dict[str, int] = {}
        self.warm: set = set()          # sources past declared warmup
        self.recorder = None            # FlightRecorder or None
        self.watchdog: Optional["LoopStallWatchdog"] = None


_state = _State()


def reset() -> None:
    """Tests only: drop violation counts, warmup declarations, the
    recorder attachment, and any running watchdog."""
    stop_watchdog()
    with _state.lock:
        _state.violations.clear()
        _state.warm.clear()
        _state.recorder = None


def attach_flight_recorder(recorder) -> None:
    """Pin future violations into `recorder` (the owning server
    attaches its monitoring FlightRecorder at startup and detaches
    with None on stop — a dead server's buffer has no debug surface
    and must not be kept alive by this global)."""
    _state.recorder = recorder


def record_violation(kind: str, detail: Dict[str, Any]) -> None:
    """Count + pin one violation.  Public so tests and the watchdog
    share one path; production code reaches it via the hooks."""
    with _state.lock:
        _state.violations[kind] = _state.violations.get(kind, 0) + 1
    from kfserving_tpu.observability import metrics as obs

    obs.sanitizer_violations_total().labels(kind=kind).inc()
    recorder = _state.recorder
    if recorder is not None:
        entry = {"sanitizer": kind}
        entry.update(detail)
        recorder.record(entry, pin=f"sanitizer_{kind}")


def violations() -> Dict[str, int]:
    with _state.lock:
        return dict(_state.violations)


def status() -> Dict[str, Any]:
    """The health-endpoint block: enabled flag, armed sources, and
    per-kind violation counts (all zero is the clean bill)."""
    with _state.lock:
        return {
            "enabled": enabled(),
            "stall_threshold_ms": _stall_threshold_ms(),
            "watchdog": _state.watchdog is not None,
            "warmed_sources": sorted(_state.warm),
            "violations": dict(_state.violations),
        }


# -- recompile-after-warmup --------------------------------------------------

def declare_warmup_complete(source: str) -> None:
    """After this, any compilation noted for `source` is a violation.
    Engines call it at the end of warmup(); harnesses call it once
    their declared warmup traffic has run."""
    if not enabled():
        return
    with _state.lock:
        _state.warm.add(source)


def note_compilation(source: str, key: Any) -> None:
    """Called (via engine/compile_cache.note_compilation) on every
    first-dispatch-per-shape.  Post-warmup notes are violations."""
    if not enabled():
        return
    with _state.lock:
        armed = source in _state.warm
    if armed:
        record_violation("recompile", {
            "source": source,
            "shape": str(key),
            "detail": "compilation after declared warmup — the "
                      "bucket grid was supposed to be closed",
        })


# -- transfer guard ----------------------------------------------------------

def _is_transfer_guard_error(exc: BaseException) -> bool:
    msg = str(exc).lower()
    return "disallow" in msg and "transfer" in msg


# Per-thread guard arming.  Two engines sharing one server loop both
# hold loop_guard across awaits, and their scopes exit in COMPLETION
# order, not LIFO — nesting two jax.transfer_guard context managers
# would let the first exit restore the pre-guard state under the
# still-running engine (disarming it) and the last exit leak
# "disallow" onto the loop forever.  Instead one underlying jax
# context manager per thread, entered at depth 0->1 and exited at
# 1->0; intermediate exits only decrement, so the guard stays armed
# exactly while any loop_guard scope is live.
_guard_tls = threading.local()


def _guard_enter() -> None:
    depth = getattr(_guard_tls, "depth", 0)
    if depth == 0:
        import jax

        cm = jax.transfer_guard("disallow")
        cm.__enter__()
        _guard_tls.cm = cm
    _guard_tls.depth = depth + 1


def _guard_exit() -> None:
    _guard_tls.depth -= 1
    if _guard_tls.depth == 0:
        cm = _guard_tls.cm
        _guard_tls.cm = None
        cm.__exit__(None, None, None)


@contextlib.contextmanager
def loop_guard(source: str = "scheduler"):
    """Arm ``jax.transfer_guard("disallow")`` for the enclosed scope
    (the generation scheduler wraps its pipeline in this, so the
    guard covers the loop thread for the engine's lifetime).  A
    disallowed transfer is counted+pinned, then re-raised."""
    if not enabled():
        yield
        return
    _guard_enter()
    try:
        yield
    except Exception as exc:
        if _is_transfer_guard_error(exc):
            record_violation("forbidden_transfer", {
                "source": source,
                "error": str(exc)[:300],
            })
        raise
    finally:
        _guard_exit()


@contextlib.contextmanager
def sanctioned_fetch():
    """The explicit-allow scope for the declared fetch points — the
    runtime twin of their line-tight ``host-sync`` pragmas.  Null
    when sanitizing is off (the production hot path pays one env
    read)."""
    if not enabled():
        yield
        return
    import jax

    with jax.transfer_guard("allow"):
        yield


# -- event-loop stall watchdog -----------------------------------------------

def _stall_threshold_ms() -> float:
    try:
        return float(os.environ.get(STALL_ENV_VAR,
                                    DEFAULT_STALL_MS))
    except ValueError:
        return DEFAULT_STALL_MS


class LoopStallWatchdog:
    """Heartbeat thread: posts a tick onto the watched loop every
    ``interval_s`` and measures how long the loop takes to run it.
    A tick older than the threshold when it finally lands (or still
    pending past the threshold at the next check) is one
    ``loop_stall`` violation per stall episode — the dynamic
    counterpart of kfslint's ``spin-loop``/``async-blocking``."""

    def __init__(self, loop, threshold_ms: Optional[float] = None,
                 interval_s: Optional[float] = None):
        self.loop = loop
        self.threshold_s = (threshold_ms
                            if threshold_ms is not None
                            else _stall_threshold_ms()) / 1000.0
        self.interval_s = interval_s or max(0.05,
                                            self.threshold_s / 2.0)
        self._sent_at: Optional[float] = None
        self._stalled = False  # one violation per episode
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="kfs-sanitize-watchdog",
            daemon=True)
        self.stalls = 0

    def start(self) -> "LoopStallWatchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _beat(self, sent_at: float) -> None:
        # Runs ON the loop: the tick landed.
        stall_s = time.perf_counter() - sent_at
        self._sent_at = None
        if stall_s > self.threshold_s:
            self._record(stall_s)
        else:
            self._stalled = False

    def _record(self, stall_s: float) -> None:
        if self._stalled:
            return  # same episode
        self._stalled = True
        self.stalls += 1
        record_violation("loop_stall", {
            "stall_ms": round(stall_s * 1000.0, 1),
            "threshold_ms": round(self.threshold_s * 1000.0, 1),
        })

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            pending = self._sent_at
            if pending is not None:
                stall_s = time.perf_counter() - pending
                if stall_s > self.threshold_s:
                    # The loop hasn't run our tick yet: it is stalled
                    # RIGHT NOW — record without waiting for release.
                    self._record(stall_s)
                continue
            sent = time.perf_counter()
            self._sent_at = sent
            try:
                self.loop.call_soon_threadsafe(self._beat, sent)
            except RuntimeError:
                return  # loop closed


def start_watchdog(loop) -> Optional[LoopStallWatchdog]:
    """Start (at most one) stall watchdog on `loop` when sanitizing.
    Returns the watchdog, or None when disabled/already running."""
    if not enabled():
        return None
    with _state.lock:
        if _state.watchdog is not None:
            return None
        wd = LoopStallWatchdog(loop)
        _state.watchdog = wd
    return wd.start()


def stop_watchdog() -> None:
    with _state.lock:
        wd, _state.watchdog = _state.watchdog, None
    if wd is not None:
        wd.stop()
