"""Shared env-knob parsing for the reliability family.

Every knob resolves `{prefix}_{infix}_{name}` first (the edge-specific
setting, e.g. `KFS_STORAGE_RETRY_MAX_ATTEMPTS`) and falls back to the
bare `KFS_{infix}_{name}` so one setting tunes every edge."""

import logging
import os

logger = logging.getLogger("kfserving_tpu.reliability")


def env_float(name: str, prefix: str, infix: str,
              default: float) -> float:
    for key in (f"{prefix}_{infix}_{name}", f"KFS_{infix}_{name}"):
        raw = os.environ.get(key)
        if raw:
            try:
                return float(raw)
            except ValueError:
                logger.warning("ignoring non-numeric %s=%r", key, raw)
    return default
