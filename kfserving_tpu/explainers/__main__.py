"""`python -m kfserving_tpu.explainers` — standalone explainer server.

The reference ships each explainer as its own server binary taking the
model name, storage URI, and predictor host on the command line
(reference python/alibiexplainer/alibiexplainer/__main__.py:29-50,
aixserver/__main__.py, artserver/__main__.py).  One entrypoint here
covers all in-tree explainer types:

    python -m kfserving_tpu.explainers \\
        --model_name iris --explainer_type anchor_tabular \\
        --storage_uri file:///path/to/artifacts \\
        --predictor_host 127.0.0.1:8080 --http_port 8081

--predictor_host defaults to $KFS_CLUSTER_LOCAL_URL/direct/predictor
(injected by the subprocess orchestrator), so an ExplainerSpec replica
needs no explicit wiring.
"""

import argparse
import logging
import os

from kfserving_tpu.explainers import EXPLAINER_TYPES, build_explainer
from kfserving_tpu.server.app import ModelServer, parser as server_parser

logging.basicConfig(level=logging.INFO)

parser = argparse.ArgumentParser(parents=[server_parser])
parser.add_argument("--model_name", default="model")
parser.add_argument("--explainer_type", default="saliency",
                    choices=EXPLAINER_TYPES)
parser.add_argument("--storage_uri", default="",
                    help="explainer artifact dir (train.npy / *.json)")
parser.add_argument("--predictor_host", default=None,
                    help="host:port[/prefix] of the predictor; defaults "
                         "to the injected cluster-local gateway")


def main(argv=None):
    args, _ = parser.parse_known_args(argv)
    predictor_host = args.predictor_host
    if not predictor_host:
        gateway = os.environ.get("KFS_CLUSTER_LOCAL_URL")
        if gateway:
            predictor_host = f"{gateway}/direct/predictor"
    model = build_explainer(args.model_name, args.explainer_type,
                            args.storage_uri, predictor_host)
    model.load()
    ModelServer(http_port=args.http_port,
                container_concurrency=args.container_concurrency
                ).start([model])


if __name__ == "__main__":
    main()
