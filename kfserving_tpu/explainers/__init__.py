"""Explainers: the reference's explainer component (reference
python/alibiexplainer wraps Alibi Anchor* behind explain(); served at
/v1/models/<m>:explain via the same ingress split,
pkg/controller/.../ingress_reconciler.go:184-217).

The TPU-native explainer is gradient saliency computed with jax.grad ON
DEVICE next to the served model — no black-box perturbation loop over
HTTP, which is what made the reference's explainers orders of magnitude
slower than predicts.  A black-box (predictor_host-proxying) explainer is
also provided for parity with the reference's deployment shape.
"""

from kfserving_tpu.explainers.adversarial import (  # noqa: F401
    AdversarialRobustness,
    SquareAttack,
)
from kfserving_tpu.explainers.anchors import (  # noqa: F401
    AnchorSearch,
    AnchorTabular,
)
from kfserving_tpu.explainers.fairness import FairnessExplainer  # noqa: F401
from kfserving_tpu.explainers.lime import (  # noqa: F401
    LimeImages,
    LimeImageSearch,
)
from kfserving_tpu.explainers.saliency import SaliencyExplainer  # noqa: F401
