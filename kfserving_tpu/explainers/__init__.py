"""Explainers: the reference's explainer component (reference
python/alibiexplainer wraps Alibi Anchor* behind explain(); served at
/v1/models/<m>:explain via the same ingress split,
pkg/controller/.../ingress_reconciler.go:184-217).

The TPU-native explainer is gradient saliency computed with jax.grad ON
DEVICE next to the served model — no black-box perturbation loop over
HTTP, which is what made the reference's explainers orders of magnitude
slower than predicts.  A black-box (predictor_host-proxying) explainer is
also provided for parity with the reference's deployment shape.
"""

from typing import Optional

from kfserving_tpu.explainers.adversarial import (  # noqa: F401
    AdversarialRobustness,
    SquareAttack,
)
from kfserving_tpu.explainers.anchor_images import (  # noqa: F401
    AnchorImages,
    AnchorImageSearch,
)
from kfserving_tpu.explainers.anchor_text import (  # noqa: F401
    AnchorText,
    AnchorTextSearch,
)
from kfserving_tpu.explainers.anchors import (  # noqa: F401
    AnchorSearch,
    AnchorTabular,
)
from kfserving_tpu.explainers.fairness import FairnessExplainer  # noqa: F401
from kfserving_tpu.explainers.lime import (  # noqa: F401
    LimeImages,
    LimeImageSearch,
)
from kfserving_tpu.explainers.saliency import SaliencyExplainer  # noqa: F401

# One dispatch table for every deployment shape: the in-process
# orchestrator factory, the standalone explainer server (__main__), and
# the subprocess command builder all resolve types here.
EXPLAINER_TYPES = ("saliency", "anchor_tabular", "anchor_images",
                   "anchor_text", "lime_images", "square_attack",
                   "fairness")
# Types whose load() dies without an artifact dir (saliency serves a
# jax model, anchors needs train.npy, fairness its group config) —
# admission validation and the subprocess command builder both reject
# missing storage_uri for these up front, where the error is visible.
ARTIFACT_REQUIRED_TYPES = ("saliency", "anchor_tabular", "fairness")


def build_explainer(name: str, explainer_type: str,
                    storage_uri: str = "",
                    predictor_host: Optional[str] = None):
    """Instantiate an in-tree explainer by type name."""
    if explainer_type == "fairness":
        # The reference aifserver takes group definitions as CLI JSON
        # args (aifserver/model.py:25-50); here they live in the
        # artifact dir like every other explainer config.
        import json
        import os

        from kfserving_tpu.storage import Storage

        if not storage_uri:
            raise ValueError(
                "fairness explainer needs a storage_uri containing "
                "fairness.json (feature_names + group definitions)")
        local = Storage.download(storage_uri)
        with open(os.path.join(local, "fairness.json")) as f:
            cfg = json.load(f)
        return FairnessExplainer(
            name,
            feature_names=cfg["feature_names"],
            privileged_groups=cfg["privileged_groups"],
            unprivileged_groups=cfg["unprivileged_groups"],
            favorable_label=cfg.get("favorable_label", 1.0),
            unfavorable_label=cfg.get("unfavorable_label", 0.0),
            n_neighbors=int(cfg.get("n_neighbors", 5)),
            predictor_host=predictor_host)
    if explainer_type == "anchor_tabular":
        return AnchorTabular(name, storage_uri,
                             predictor_host=predictor_host)
    if explainer_type == "anchor_images":
        return AnchorImages(name, storage_uri,
                            predictor_host=predictor_host)
    if explainer_type == "anchor_text":
        return AnchorText(name, storage_uri,
                          predictor_host=predictor_host)
    if explainer_type == "lime_images":
        return LimeImages(name, storage_uri,
                          predictor_host=predictor_host)
    if explainer_type == "square_attack":
        return AdversarialRobustness(name, storage_uri,
                                     predictor_host=predictor_host)
    if explainer_type == "saliency":
        model = SaliencyExplainer(name, storage_uri)
        if predictor_host:
            model.predictor_host = predictor_host
        return model
    raise ValueError(
        f"unknown explainer_type {explainer_type!r} "
        f"(one of {list(EXPLAINER_TYPES)}, or set an explicit command)")
