"""Native LIME image explainer (aixexplainer parity).

The reference serves AIX360's LimeImageExplainer behind `:explain`
(reference python/aixexplainer/aixserver/model.py:25-110: segment the
image into superpixels, perturb by masking segments, fit a local linear
surrogate on the predictor's outputs, return per-label superpixel
masks).  This is a first-party implementation of the same artifact with
no lime/aix360/skimage dependency:

- segmentation is a native grid superpixel partition (the reference
  defaults to skimage quickshift; the surrogate fit is the content of
  LIME, the segmenter just needs locality);
- every perturbation batch is ONE predictor call, riding this stack's
  dynamic batcher and padded TPU buckets (lime's default loops in
  chunks of 10);
- the local model is an exponentially-kernel-weighted ridge regression
  solved in closed form per label.

Response contract matches the reference handler: {"explanations":
{"temp": <image>, "masks": [per-label masks], "top_labels": [...]}}
(aixserver/model.py:96-105).
"""

import inspect
import json
import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from kfserving_tpu.explainers.proxy import PredictorProxyModel
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InvalidInput

logger = logging.getLogger("kfserving_tpu.explainers.lime")


def grid_segments(shape: Tuple[int, int], n_segments: int = 64
                  ) -> np.ndarray:
    """[H, W] int32 superpixel labels: a ceil(sqrt(n))^2 grid."""
    h, w = shape
    side = max(1, int(round(n_segments ** 0.5)))
    rows = np.minimum((np.arange(h) * side) // max(h, 1), side - 1)
    cols = np.minimum((np.arange(w) * side) // max(w, 1), side - 1)
    return (rows[:, None] * side + cols[None, :]).astype(np.int32)


def _ridge(x: np.ndarray, y: np.ndarray, weights: np.ndarray,
           alpha: float = 1.0) -> np.ndarray:
    """Weighted ridge fit; returns coefficients (no intercept term in
    the output — LIME ranks features by coefficient magnitude)."""
    xw = x * weights[:, None]
    # Append intercept column so segment weights aren't forced to soak
    # up the base rate.
    ones = np.ones((len(x), 1))
    xa = np.concatenate([x, ones], axis=1)
    xwa = np.concatenate([xw, weights[:, None]], axis=1)
    gram = xwa.T @ xa + alpha * np.eye(xa.shape[1])
    coef = np.linalg.solve(gram, xwa.T @ y)
    return coef[:-1]


class LimeImageSearch:
    """Sample-perturb-fit loop over one image.

    predict_fn: batch [n, H, W, C] -> probabilities [n, k] (or labels
        [n], one-hot'd here — the reference tolerates both through its
        predictor proxy).
    """

    def __init__(self, predict_fn: Callable,
                 n_segments: int = 64,
                 kernel_width: float = 0.25,
                 hide_color: float = 0.0,
                 seed: int = 0):
        self.predict_fn = predict_fn
        self.n_segments = n_segments
        self.kernel_width = kernel_width
        self.hide_color = hide_color
        self.rng = np.random.default_rng(seed)

    async def _raw(self, batch: np.ndarray) -> np.ndarray:
        out = self.predict_fn(batch)
        if inspect.isawaitable(out):
            out = await out
        return np.asarray(out)

    async def explain(self, image: np.ndarray,
                      num_samples: int = 256,
                      top_labels: int = 2,
                      num_features: int = 10,
                      positive_only: bool = True,
                      min_weight: float = 0.0,
                      batch_size: int = 64) -> Dict[str, Any]:
        if image.ndim == 2:
            image = image[..., None]
        if image.ndim != 3:
            raise InvalidInput(
                f"LIME images needs [H, W, C] or [H, W], got shape "
                f"{list(image.shape)}")
        segments = grid_segments(image.shape[:2], self.n_segments)
        seg_ids = np.unique(segments)
        s = len(seg_ids)
        onehot = (segments[None, ...] == seg_ids[:, None, None])

        # Binary presence vectors; first row = unperturbed image.
        z = self.rng.integers(0, 2, size=(num_samples, s)).astype(
            np.float64)
        z[0] = 1.0
        background = np.full_like(image, self.hide_color,
                                  dtype=image.dtype)
        raws = []
        for start in range(0, num_samples, batch_size):
            chunk = z[start:start + batch_size]
            # [b, H, W] pixel keep-mask from segment presence
            keep = np.einsum("bs,shw->bhw", chunk, onehot) > 0
            batch = np.where(keep[..., None], image[None], background)
            raws.append(await self._raw(batch))
        if raws[0].ndim == 1:
            # Label outputs: one-hot AFTER concatenation so the class
            # width is global, not per-chunk (chunks that happen not to
            # observe the top class would otherwise disagree in width).
            labels = np.concatenate(raws).astype(np.int64)
            y = np.eye(max(int(labels.max()) + 1, 2))[labels]
        else:
            y = np.concatenate(
                [np.asarray(r, np.float64) for r in raws], axis=0)

        # Exponential kernel on cosine distance to the full image.
        frac = z.sum(axis=1) / s
        dist = 1.0 - frac  # cosine distance to all-ones for binary z
        # _ridge applies this once when forming the normal equations
        # (gram = (X*w)^T X), so it must be the full kernel value, not
        # its square root, for the solved system to be
        # X^T diag(kernel) X (LIME's weighted least squares).
        weights = np.exp(-(dist ** 2) / self.kernel_width ** 2)

        order = np.argsort(y[0])[::-1][:top_labels]
        masks: List[List[List[int]]] = []
        weights_out = []
        for label in order:
            coef = _ridge(z, y[:, label], weights)
            rank = np.argsort(np.abs(coef))[::-1]
            chosen = []
            for j in rank[:num_features]:
                if positive_only and coef[j] <= 0:
                    continue
                if abs(coef[j]) < min_weight:
                    continue
                chosen.append(j)
            mask = np.zeros(segments.shape, np.int32)
            for j in chosen:
                mask[segments == seg_ids[j]] = 1 if coef[j] > 0 else -1
            masks.append(mask.tolist())
            weights_out.append(
                {str(int(seg_ids[j])): float(coef[j]) for j in chosen})
        return {
            "temp": image.tolist(),
            "masks": masks,
            "top_labels": [int(c) for c in order],
            "segment_weights": weights_out,
        }


class LimeImages(PredictorProxyModel):
    """Served LIME explainer: `:explain` with predictor proxying (the
    aixexplainer deployment shape, aixserver/model.py:44-50).

    Artifact layout (`storage_uri`, all optional):
        lime.json — {"n_segments": 64, "num_samples": 256,
                     "top_labels": 2, "positive_only": true,
                     "min_weight": 0.0, "kernel_width": 0.25}
    """

    def __init__(self, name: str, model_dir: str = "",
                 predictor_host: Optional[str] = None,
                 predict_fn: Optional[Callable] = None):
        super().__init__(name, predictor_host=predictor_host,
                         predict_fn=predict_fn)
        self.model_dir = model_dir
        self.config: Dict[str, Any] = {}
        self.search: Optional[LimeImageSearch] = None

    def load(self) -> bool:
        _, self.config = self._load_artifact_dir(self.model_dir,
                                                 "lime.json")
        self.search = LimeImageSearch(
            self._proxied_predict,
            n_segments=int(self.config.get("n_segments", 64)),
            kernel_width=float(self.config.get("kernel_width", 0.25)),
            hide_color=float(self.config.get("hide_color", 0.0)),
            seed=int(self.config.get("seed", 0)))
        self.ready = True
        return True

    async def explain(self, request: Any) -> Any:
        if self.search is None:
            raise InvalidInput(f"explainer {self.name} not loaded")
        instances = v1.get_instances(request)
        if not instances:
            raise InvalidInput("LIME explainer needs one instance")
        # Per-request parameter overrides, same knobs as the reference
        # handler (aixserver/model.py:55-70).
        req = request if isinstance(request, dict) else {}
        explanation = await self.search.explain(
            np.asarray(instances[0], np.float64),
            num_samples=int(req.get(
                "num_samples", self.config.get("num_samples", 256))),
            top_labels=int(req.get(
                "top_labels", self.config.get("top_labels", 2))),
            num_features=int(req.get(
                "num_features", self.config.get("num_features", 10))),
            positive_only=bool(req.get(
                "positive_only", self.config.get("positive_only", True))),
            min_weight=float(req.get(
                "min_weight", self.config.get("min_weight", 0.0))),
            batch_size=int(self.config.get("batch_size", 64)))
        return {"explanations": explanation}
