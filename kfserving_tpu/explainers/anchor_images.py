"""Native anchor-images explainer: high-precision superpixel sets.

The reference serves alibi's AnchorImage behind `:explain` (reference
python/alibiexplainer/alibiexplainer/anchor_images.py:26-50 — wraps a
built alibi.explainers.AnchorImage, argmax-adapts probability
predictors, explains inputs[0]; dispatch explainer.py:57-58).  This is
a first-party implementation of the same artifact: the smallest set of
superpixels whose presence alone keeps the model's prediction, with
precision estimated by Monte-Carlo segment dropout through the live
predictor.

Anchor semantics (Ribeiro 2018 §2, image instantiation):
- predicates are "superpixel j shows the original pixels";
- a perturbation keeps each non-anchored segment with probability
  p_sample and replaces dropped segments with the segment's mean color
  (alibi's default fudged-image fill);
- precision(A) = P[f(perturbed) == f(x)], coverage(A) = p_sample^|A| —
  the exact probability a random perturbation pattern satisfies the
  anchor under the sampling distribution (alibi estimates the same
  quantity from a sample of patterns).

Segmentation is the native grid partition shared with LIME images
(`lime.grid_segments`); the beam search, candidate coalescing (one
predictor round trip per beam level) and 5x confirmation are the shared
`anchors.beam_anchor_search`.
"""

import logging
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from kfserving_tpu.explainers.anchors import (
    beam_anchor_search,
    call_labels,
    estimate_precisions,
)
from kfserving_tpu.explainers.lime import grid_segments
from kfserving_tpu.explainers.proxy import PredictorProxyModel
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InvalidInput

logger = logging.getLogger("kfserving_tpu.explainers.anchor_images")


class AnchorImageSearch:
    """Beam search for the smallest high-precision superpixel anchor.

    predict_fn: (sync or async) image batch [n, H, W, C] -> labels [n]
        (or probabilities [n, k], argmax'd — the reference argmax-wraps
        the same two cases, anchor_images.py:42-45).
    """

    def __init__(self, predict_fn: Callable,
                 n_segments: int = 36,
                 p_sample: float = 0.5,
                 max_call_bytes: int = 64 << 20,
                 seed: int = 0):
        self.predict_fn = predict_fn
        self.n_segments = n_segments
        if not 0.0 < p_sample < 1.0:
            raise InvalidInput(
                f"p_sample must be in (0, 1), got {p_sample}")
        self.p_sample = p_sample
        # Image rows are large (a 224x224x3 float64 frame is ~1.2 MB);
        # an unbounded level coalescing would concatenate gigabytes.
        # The shared estimator chunks transport at this budget while
        # keeping one logical estimate per beam level.
        self.max_call_bytes = int(max_call_bytes)
        self.rng = np.random.default_rng(seed)

    def _perturb(self, image: np.ndarray, onehot: np.ndarray,
                 mean_fill: np.ndarray, anchor: Tuple[int, ...],
                 n: int) -> np.ndarray:
        """n images: anchored segments original, the rest dropped to
        the mean fill with probability 1 - p_sample."""
        d = onehot.shape[0]
        keep = self.rng.random((n, d)) < self.p_sample
        keep[:, list(anchor)] = True
        # [n, H, W] pixel keep-mask from segment presence
        pixel_keep = np.einsum("ns,shw->nhw", keep.astype(np.float64),
                               onehot.astype(np.float64)) > 0
        return np.where(pixel_keep[..., None], image[None],
                        mean_fill[None])

    async def explain(self, image: Any, threshold: float = 0.95,
                      batch_size: int = 24, beam_size: int = 2,
                      max_anchor_size: Optional[int] = None
                      ) -> Dict[str, Any]:
        image = np.asarray(image, np.float64)
        if image.ndim == 2:
            image = image[..., None]
        if image.ndim != 3:
            raise InvalidInput(
                f"anchor images needs [H, W, C] or [H, W], got shape "
                f"{list(image.shape)}")
        segments = grid_segments(image.shape[:2], self.n_segments)
        seg_ids = np.unique(segments)
        d = len(seg_ids)
        onehot = (segments[None, ...] == seg_ids[:, None, None])
        # Per-segment mean color fill (alibi's default perturbation).
        mean_fill = np.empty_like(image)
        for s in range(d):
            mean_fill[onehot[s]] = image[onehot[s]].mean(axis=0)

        label = int((await call_labels(self.predict_fn,
                                       image[None]))[0])
        row_cap = max(1, self.max_call_bytes // max(1, image.nbytes))

        async def estimate_many(anchors: Sequence[Tuple[int, ...]],
                                n: int) -> Dict[Tuple[int, ...], float]:
            return await estimate_precisions(
                self.predict_fn,
                lambda a, k: self._perturb(image, onehot, mean_fill,
                                           a, k),
                label, anchors, n, max_rows_per_call=row_cap)

        base_prec = (await estimate_many([()], batch_size))[()]
        if base_prec >= threshold:
            return self._result(segments, seg_ids, label, (), base_prec,
                                True)
        anchor, prec, met = await beam_anchor_search(
            d, estimate_many,
            lambda a: float(self.p_sample ** len(a)),
            base_prec, threshold, batch_size, beam_size,
            max_anchor_size or d)
        return self._result(segments, seg_ids, label, anchor, prec, met)

    def _result(self, segments, seg_ids, label, anchor, precision,
                met) -> Dict[str, Any]:
        mask = np.isin(segments, seg_ids[list(anchor)]) if anchor \
            else np.zeros_like(segments, bool)
        return {
            # alibi's Explanation carries the anchor as image mask +
            # segment labels; ids keep the payload compact.
            "anchor_segments": [int(seg_ids[j]) for j in anchor],
            "mask": mask.astype(np.int32).tolist(),
            "segments": segments.tolist(),
            "precision": round(float(precision), 4),
            "coverage": round(float(self.p_sample ** len(anchor)), 4),
            "prediction": label,
            "met_threshold": met,
        }


class AnchorImages(PredictorProxyModel):
    """Served anchor-images explainer (`:explain`, predictor proxied —
    the alibiexplainer deployment shape, explainer.py:57-58).

    Artifact layout (`storage_uri`, entirely optional):
        anchor_images.json — {"n_segments": 36, "p_sample": 0.5,
                              "precision_threshold": 0.95,
                              "batch_size": 24, "beam_size": 2,
                              "max_anchor_size": null, "seed": 0}
    """

    def __init__(self, name: str, model_dir: str = "",
                 predictor_host: Optional[str] = None,
                 predict_fn: Optional[Callable] = None):
        super().__init__(name, predictor_host=predictor_host,
                         predict_fn=predict_fn)
        self.model_dir = model_dir
        self.config: Dict[str, Any] = {}
        self.search: Optional[AnchorImageSearch] = None

    def load(self) -> bool:
        _, self.config = self._load_artifact_dir(self.model_dir,
                                                 "anchor_images.json")
        self.search = AnchorImageSearch(
            self._proxied_predict,
            n_segments=int(self.config.get("n_segments", 36)),
            p_sample=float(self.config.get("p_sample", 0.5)),
            max_call_bytes=int(self.config.get("max_call_bytes",
                                               64 << 20)),
            seed=int(self.config.get("seed", 0)))
        self.ready = True
        return True

    async def explain(self, request: Any) -> Any:
        if self.search is None:
            raise InvalidInput(f"explainer {self.name} not loaded")
        instances = v1.get_instances(request)
        if not instances:
            raise InvalidInput("anchor images needs one instance")
        max_size = self.config.get("max_anchor_size")
        explanation = await self.search.explain(
            np.asarray(instances[0], np.float64),
            threshold=float(self.config.get("precision_threshold",
                                            0.95)),
            batch_size=int(self.config.get("batch_size", 24)),
            beam_size=int(self.config.get("beam_size", 2)),
            max_anchor_size=None if max_size is None else int(max_size))
        return {
            "meta": {"name": "AnchorImages"},
            "data": explanation,
        }
