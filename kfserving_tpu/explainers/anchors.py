"""Native anchors explainer: precision-guided IF-THEN rules (tabular).

The reference's flagship explainer is alibi AnchorTabular served by
alibiexplainer (reference
python/alibiexplainer/alibiexplainer/explainer.py:39-100, anchor
dispatch :55-66; anchor_tabular.py wraps alibi.explainers.AnchorTabular
and proxies model calls through the predictor, explainer.py:66-76).
This is a first-party implementation of the same artifact — an anchor
rule

    IF petal_len <= 1.57 AND petal_w <= 0.4 THEN predict setosa
    (precision 0.99, coverage 0.31)

found by beam search over discretized feature predicates, with
precision estimated by Monte-Carlo perturbation through the live
predictor (Ribeiro et al. 2018, "Anchors: High-Precision
Model-Agnostic Explanations").

Differences from alibi, by design:
- the sampler and beam search are ~200 lines of numpy with *coalesced*
  predictor calls — every beam level's candidate set (d features x beam
  width precision estimates) is ONE `predict(batch)` round trip, with
  the labels sliced back per candidate, so `:explain` latency scales
  with anchor size, not candidate count (alibi's sampler loops
  row-by-row);
- precision confirmation is a fixed-budget re-estimate, not KL-LUCB
  (serving-grade simplicity; the confirm batch is 5x the search batch).
"""

import inspect
import logging
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kfserving_tpu.explainers.proxy import PredictorProxyModel
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InvalidInput

logger = logging.getLogger("kfserving_tpu.explainers.anchors")


async def call_labels(predict_fn: Callable, batch) -> np.ndarray:
    """Run a (sync or async) predictor and normalize to class labels
    [n] — probability/logit outputs are argmax'd, matching the
    reference's ArgmaxTransformer wrap (anchor_tabular.py:47-56).
    Shared by all three anchor modalities."""
    out = predict_fn(batch)
    if inspect.isawaitable(out):
        out = await out
    out = np.asarray(out)
    if out.ndim > 1:
        out = np.argmax(out, axis=-1)
    return out.reshape(-1)


async def estimate_precisions(predict_fn: Callable,
                              sample_fn: Callable,
                              label,
                              anchors: Sequence[Tuple[int, ...]],
                              n: int,
                              max_rows_per_call: Optional[int] = None
                              ) -> Dict[Tuple[int, ...], float]:
    """Estimate every anchor's precision with COALESCED predictor round
    trips: each anchor's n perturbations (from sample_fn(anchor, k) —
    ndarray rows or a list, e.g. perturbed sentences) are packed into as
    few predict calls as max_rows_per_call allows — exactly one when
    unbounded.  d features x beam width estimates per beam level
    therefore cost one HTTP hop (one padded TPU bucket dispatch), not
    d x beam; the row cap exists for modalities whose rows are large
    (full images), where a single unbounded concatenation would be
    gigabytes.
    """
    if not anchors:
        return {}
    cap = max(1, max_rows_per_call or len(anchors) * n)
    # Work list of (anchor, k) pieces; an anchor whose n exceeds the
    # cap is split across calls and its hit slices re-joined below.
    pieces: List[Tuple[Tuple[int, ...], int]] = []
    for a in anchors:
        remaining = n
        # kfslint: disable=spin-loop — bounded arithmetic split (take
        # >= 1 every pass); no external coroutine gates the exit.
        while remaining > 0:
            take = min(remaining, cap)
            pieces.append((a, take))
            remaining -= take
    hits: Dict[Tuple[int, ...], List[np.ndarray]] = {a: [] for a in anchors}
    buf: List[Any] = []
    meta: List[Tuple[Tuple[int, ...], int]] = []

    async def flush() -> None:
        if not buf:
            return
        if isinstance(buf[0], np.ndarray):
            z: Any = np.concatenate(buf, axis=0)
        else:
            z = [row for piece in buf for row in piece]
        labels = await call_labels(predict_fn, z)
        i = 0
        for a, k in meta:
            hits[a].append(np.asarray(labels[i:i + k]) == label)
            i += k
        buf.clear()
        meta.clear()

    rows = 0
    for a, k in pieces:
        if rows + k > cap and buf:
            await flush()
            rows = 0
        buf.append(sample_fn(a, k))
        meta.append((a, k))
        rows += k
    await flush()
    return {a: float(np.mean(np.concatenate(hits[a]))) for a in anchors}


async def beam_anchor_search(d: int,
                             estimate_many: Callable,
                             coverage_fn: Callable,
                             base_precision: float,
                             threshold: float,
                             batch_size: int,
                             beam_size: int,
                             max_size: int):
    """Shared precision-guided beam search over d boolean predicates.

    The modality-specific part of every anchor explainer (tabular
    predicates, image superpixels, text tokens) is only its sampler and
    coverage measure; the search itself — expand the beam, estimate all
    candidates' precision in ONE coalesced predictor call, confirm
    passing anchors at 5x budget, prefer widest coverage — is identical
    (Ribeiro 2018 §3; the reference reuses alibi's one AnchorBaseBeam
    the same way, alibi explainers/anchor_base.py).

    estimate_many(anchors, n) -> {anchor: precision} must issue a
    single predict round trip for the whole level.
    Returns (anchor, precision, met_threshold).
    """
    beam: List[Tuple[Tuple[int, ...], float]] = [((), base_precision)]
    best: Optional[Tuple[Tuple[int, ...], float]] = None
    for _ in range(max_size):
        expansions: List[Tuple[int, ...]] = []
        seen = set()
        for anchor, _ in beam:
            for j in range(d):
                if j in anchor:
                    continue
                cand = tuple(sorted(anchor + (j,)))
                if cand not in seen:
                    seen.add(cand)
                    expansions.append(cand)
        candidates = await estimate_many(expansions, batch_size)
        if not candidates:
            break
        ranked = sorted(candidates.items(),
                        key=lambda kv: (-kv[1], len(kv[0])))
        passing = [c for c in ranked if c[1] >= threshold]
        if passing:
            # Confirm with a 5x budget (one more coalesced call);
            # prefer the widest-coverage confirmed anchor of this
            # (smallest passing) size.
            finalists = [a for a, _ in passing[:beam_size + 1]]
            confirm = await estimate_many(finalists, batch_size * 5)
            confirmed = []
            for anchor, prec in confirm.items():
                if prec >= threshold:
                    confirmed.append((anchor, prec, coverage_fn(anchor)))
            if confirmed:
                confirmed.sort(key=lambda t: -t[2])
                anchor, prec, _ = confirmed[0]
                return anchor, prec, True
        beam = ranked[:beam_size]
        if best is None or beam[0][1] > best[1]:
            best = beam[0]
    # No anchor met the threshold (noisy boundary instance): return the
    # best found, flagged — the reference surfaces alibi's best-effort
    # result the same way.
    anchor, prec = best if best else ((), base_precision)
    return anchor, prec, False


class AnchorSearch:
    """Beam search for the smallest high-precision anchor.

    predict_fn: (sync or async) batch [n, d] -> class labels [n] (or
        probabilities [n, k], argmax'd here — the reference wraps the
        same two cases, anchor_tabular.py:47-56).
    train_data: [m, d] background sample defining the perturbation
        distribution and coverage.
    """

    def __init__(self, predict_fn: Callable,
                 train_data: np.ndarray,
                 feature_names: Optional[Sequence[str]] = None,
                 categorical_features: Optional[Sequence[int]] = None,
                 n_bins: int = 4,
                 seed: int = 0):
        self.predict_fn = predict_fn
        self.train = np.asarray(train_data, np.float64)
        if self.train.ndim != 2:
            raise InvalidInput("train_data must be [rows, features]")
        m, d = self.train.shape
        self.feature_names = (list(feature_names) if feature_names
                              else [f"f{j}" for j in range(d)])
        self.categorical = set(categorical_features or ())
        self.rng = np.random.default_rng(seed)
        # Quantile discretization for numeric features (alibi uses the
        # same quartile default).
        self.bin_edges: Dict[int, np.ndarray] = {}
        for j in range(d):
            if j in self.categorical:
                continue
            qs = np.quantile(self.train[:, j],
                             np.linspace(0, 1, n_bins + 1)[1:-1])
            self.bin_edges[j] = np.unique(qs)

    # -- predicates --------------------------------------------------------
    def _bin_of(self, j: int, value: float) -> int:
        if j in self.categorical:
            return int(value)
        # right=True makes bins (lo, hi], agreeing with _predicate_mask
        # and the "<=" rule text — a value sitting exactly on a quantile
        # edge must land in the bin its own anchor covers.
        return int(np.digitize(value, self.bin_edges[j], right=True))

    def _predicate_mask(self, j: int, b: int,
                        data: np.ndarray) -> np.ndarray:
        """Rows of `data` whose feature j falls in bin b."""
        col = data[:, j]
        if j in self.categorical:
            return col == b
        edges = self.bin_edges[j]
        lo = -np.inf if b == 0 else edges[b - 1]
        hi = np.inf if b == len(edges) else edges[b]
        return (col > lo) & (col <= hi)

    def _describe(self, j: int, b: int) -> str:
        name = self.feature_names[j]
        if j in self.categorical:
            return f"{name} = {b}"
        edges = self.bin_edges[j]
        if b == 0:
            return f"{name} <= {edges[0]:.2f}"
        if b == len(edges):
            return f"{name} > {edges[-1]:.2f}"
        return f"{edges[b - 1]:.2f} < {name} <= {edges[b]:.2f}"

    # -- sampling ----------------------------------------------------------
    def _sample(self, x: np.ndarray, anchor: Tuple[int, ...],
                n: int) -> np.ndarray:
        """Perturbations conditioned on the anchor: anchored features
        take values from the same bin as x (from the background pool,
        falling back to x's value), free features take whole background
        rows — the paper's D(z|A)."""
        idx = self.rng.integers(0, len(self.train), size=n)
        z = self.train[idx].copy()
        for j in anchor:
            b = self._bin_of(j, x[j])
            pool = self.train[self._predicate_mask(j, b, self.train), j]
            if len(pool):
                z[:, j] = self.rng.choice(pool, size=n)
            else:
                z[:, j] = x[j]
        return z

    async def _labels(self, batch: np.ndarray) -> np.ndarray:
        return await call_labels(self.predict_fn, batch)

    async def _precision(self, x: np.ndarray, label,
                         anchor: Tuple[int, ...], n: int) -> float:
        out = await self._precision_many(x, label, [anchor], n)
        return out[anchor]

    async def _precision_many(self, x: np.ndarray, label,
                              anchors: Sequence[Tuple[int, ...]],
                              n: int) -> Dict[Tuple[int, ...], float]:
        return await estimate_precisions(
            self.predict_fn,
            lambda anchor, k: self._sample(x, anchor, k),
            label, anchors, n)

    def _coverage(self, x: np.ndarray, anchor: Tuple[int, ...]) -> float:
        mask = np.ones(len(self.train), bool)
        for j in anchor:
            mask &= self._predicate_mask(j, self._bin_of(j, x[j]),
                                         self.train)
        return float(np.mean(mask))

    # -- search ------------------------------------------------------------
    async def explain(self, x: Any, threshold: float = 0.95,
                      batch_size: int = 128, beam_size: int = 2,
                      max_anchor_size: Optional[int] = None
                      ) -> Dict[str, Any]:
        x = np.asarray(x, np.float64).reshape(-1)
        d = x.shape[0]
        if d != self.train.shape[1]:
            raise InvalidInput(
                f"instance has {d} features, train_data has "
                f"{self.train.shape[1]}")
        label = (await self._labels(x[None]))[0]
        max_size = max_anchor_size or d

        # Empty anchor short-circuit: the model may predict this class
        # for most of the distribution already.
        base_prec = await self._precision(x, label, (), batch_size)
        if base_prec >= threshold:
            return self._result(x, label, (), base_prec)

        anchor, prec, met = await beam_anchor_search(
            d,
            lambda anchors, n: self._precision_many(x, label, anchors, n),
            lambda anchor: self._coverage(x, anchor),
            base_prec, threshold, batch_size, beam_size, max_size)
        return self._result(x, label, anchor, prec, met_threshold=met)

    def _result(self, x, label, anchor, precision,
                met_threshold: bool = True) -> Dict[str, Any]:
        return {
            "anchor": [self._describe(j, self._bin_of(j, x[j]))
                       for j in anchor],
            "feature_indices": list(anchor),
            "precision": round(precision, 4),
            "coverage": round(self._coverage(x, anchor), 4),
            "prediction": int(label) if np.ndim(label) == 0 else label,
            "met_threshold": met_threshold,
        }


class AnchorTabular(PredictorProxyModel):
    """Served anchors explainer: sits on `:explain` and proxies model
    calls to the predictor (the alibiexplainer deployment shape:
    explainer.py:66-76 builds predict_fn from predictor_host).

    Artifact layout (`storage_uri`):
        anchors.json — {"feature_names": [...], "precision_threshold":
                        0.95, "batch_size": 128, "n_bins": 4,
                        "categorical_features": [...]}  (all optional)
        train.npy    — [m, d] background data (required)
    """

    def __init__(self, name: str, model_dir: str,
                 predictor_host: Optional[str] = None,
                 predict_fn: Optional[Callable] = None):
        super().__init__(name, predictor_host=predictor_host,
                         predict_fn=predict_fn)
        self.model_dir = model_dir
        self.search: Optional[AnchorSearch] = None
        self.config: Dict[str, Any] = {}

    def load(self) -> bool:
        local, self.config = self._load_artifact_dir(self.model_dir,
                                                     "anchors.json")
        if local is None:
            raise InvalidInput(
                "anchors explainer needs a storage_uri with train.npy")
        train_path = os.path.join(local, "train.npy")
        if not os.path.exists(train_path):
            raise InvalidInput(
                f"anchors explainer needs train.npy in {self.model_dir}")
        train = np.load(train_path)
        self.search = AnchorSearch(
            self._proxied_predict,
            train,
            feature_names=self.config.get("feature_names"),
            categorical_features=self.config.get("categorical_features"),
            n_bins=int(self.config.get("n_bins", 4)),
            seed=int(self.config.get("seed", 0)))
        self.ready = True
        return True

    async def explain(self, request: Any) -> Any:
        if self.search is None:
            raise InvalidInput(f"explainer {self.name} not loaded")
        instances = v1.get_instances(request)
        explanation = await self.search.explain(
            np.asarray(instances[0], np.float64),
            threshold=float(self.config.get("precision_threshold", 0.95)),
            batch_size=int(self.config.get("batch_size", 128)),
            beam_size=int(self.config.get("beam_size", 2)),
            max_anchor_size=self.config.get("max_anchor_size"))
        # alibi Explanation JSON shape: meta + data (explainer.py:84-87
        # returns it verbatim); the anchor payload lives under data.
        return {
            "meta": {"name": "AnchorTabular"},
            "data": explanation,
        }
