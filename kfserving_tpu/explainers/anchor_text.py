"""Native anchor-text explainer: high-precision token sets.

The reference serves alibi's AnchorText behind `:explain` (reference
python/alibiexplainer/alibiexplainer/anchor_text.py:28-61 — loads a
spacy language model, argmax-adapts probability predictors, explains
inputs[0]; dispatch explainer.py:59-60).  This is a first-party
implementation of the same artifact with no spacy dependency: the
smallest set of tokens whose presence alone keeps the classifier's
prediction.

Anchor semantics (Ribeiro 2018 §2, text instantiation; alibi's
use_unk=True default path, which needs no synonym embeddings):
- tokenization is whitespace splitting (the reference needs spacy only
  for its similarity-sampling mode; UNK-mode perturbation is
  tokenizer-agnostic);
- a perturbation keeps each non-anchored token with probability
  p_sample and replaces dropped tokens with a mask token ("UNK");
- precision(A) = P[f(perturbed) == f(x)], coverage(A) = p_sample^|A|
  (exact under the sampling distribution).

The beam search with coalesced per-level predictor calls is the shared
`anchors.beam_anchor_search`; every level's perturbed sentences ride
ONE predict round trip.
"""

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kfserving_tpu.explainers.anchors import (
    beam_anchor_search,
    call_labels,
    estimate_precisions,
)
from kfserving_tpu.explainers.proxy import PredictorProxyModel
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InvalidInput

logger = logging.getLogger("kfserving_tpu.explainers.anchor_text")


class AnchorTextSearch:
    """Beam search for the smallest high-precision token anchor.

    predict_fn: (sync or async) list of n strings -> labels [n] (or
        probabilities [n, k], argmax'd — the reference argmax-wraps the
        same two cases, anchor_text.py:53-58).
    """

    def __init__(self, predict_fn: Callable,
                 unk_token: str = "UNK",
                 p_sample: float = 0.5,
                 max_call_bytes: int = 8 << 20,
                 seed: int = 0):
        self.predict_fn = predict_fn
        self.unk_token = unk_token
        if not 0.0 < p_sample < 1.0:
            raise InvalidInput(
                f"p_sample must be in (0, 1), got {p_sample}")
        self.p_sample = p_sample
        # Long documents inflate fast: d candidates x batch_size
        # doc-sized sentences in one JSON POST can pass the server's
        # 100 MB body cap (http.py MAX_BODY_BYTES).  The shared
        # estimator chunks transport at this budget while keeping one
        # logical estimate per beam level.
        self.max_call_bytes = int(max_call_bytes)
        self.rng = np.random.default_rng(seed)

    async def _labels(self, batch: List[str]) -> np.ndarray:
        return await call_labels(self.predict_fn, batch)

    def _perturb(self, tokens: List[str], anchor: Tuple[int, ...],
                 n: int) -> List[str]:
        d = len(tokens)
        keep = self.rng.random((n, d)) < self.p_sample
        if anchor:
            keep[:, list(anchor)] = True
        toks = np.array(tokens, dtype=object)
        unk = np.array([self.unk_token] * d, dtype=object)
        return [" ".join(np.where(keep[i], toks, unk).tolist())
                for i in range(n)]

    async def explain(self, text: str, threshold: float = 0.95,
                      batch_size: int = 64, beam_size: int = 2,
                      max_anchor_size: Optional[int] = None
                      ) -> Dict[str, Any]:
        if not isinstance(text, str) or not text.strip():
            raise InvalidInput("anchor text needs a non-empty string")
        tokens = text.split()
        d = len(tokens)
        label = int((await self._labels([text]))[0])
        # A perturbed row is at most the document plus UNK growth per
        # token; JSON escaping adds a little more.
        row_bytes = len(text.encode()) \
            + d * (len(self.unk_token) + 4) + 16
        row_cap = max(1, self.max_call_bytes // row_bytes)

        async def estimate_many(anchors: Sequence[Tuple[int, ...]],
                                n: int) -> Dict[Tuple[int, ...], float]:
            return await estimate_precisions(
                self.predict_fn,
                lambda a, k: self._perturb(tokens, a, k),
                label, anchors, n, max_rows_per_call=row_cap)

        base_prec = (await estimate_many([()], batch_size))[()]
        if base_prec >= threshold:
            return self._result(tokens, label, (), base_prec, True)
        anchor, prec, met = await beam_anchor_search(
            d, estimate_many,
            lambda a: float(self.p_sample ** len(a)),
            base_prec, threshold, batch_size, beam_size,
            max_anchor_size or d)
        return self._result(tokens, label, anchor, prec, met)

    def _result(self, tokens, label, anchor, precision,
                met) -> Dict[str, Any]:
        return {
            # alibi's text Explanation carries the anchor words; the
            # positions disambiguate repeated words.
            "anchor": [tokens[j] for j in anchor],
            "positions": list(anchor),
            "precision": round(float(precision), 4),
            "coverage": round(float(self.p_sample ** len(anchor)), 4),
            "prediction": label,
            "met_threshold": met,
        }


class AnchorText(PredictorProxyModel):
    """Served anchor-text explainer (`:explain`, predictor proxied —
    the alibiexplainer deployment shape, explainer.py:59-60).

    Artifact layout (`storage_uri`, entirely optional):
        anchor_text.json — {"unk_token": "UNK", "p_sample": 0.5,
                            "precision_threshold": 0.95,
                            "batch_size": 64, "beam_size": 2,
                            "max_anchor_size": null, "seed": 0}
    """

    def __init__(self, name: str, model_dir: str = "",
                 predictor_host: Optional[str] = None,
                 predict_fn: Optional[Callable] = None):
        super().__init__(name, predictor_host=predictor_host,
                         predict_fn=predict_fn)
        self.model_dir = model_dir
        self.config: Dict[str, Any] = {}
        self.search: Optional[AnchorTextSearch] = None

    def load(self) -> bool:
        _, self.config = self._load_artifact_dir(self.model_dir,
                                                 "anchor_text.json")
        self.search = AnchorTextSearch(
            self._predict_strings,
            unk_token=str(self.config.get("unk_token", "UNK")),
            p_sample=float(self.config.get("p_sample", 0.5)),
            max_call_bytes=int(self.config.get("max_call_bytes",
                                               8 << 20)),
            seed=int(self.config.get("seed", 0)))
        self.ready = True
        return True

    async def _predict_strings(self, batch: List[str]):
        # Text payloads stay a plain JSON list (the V2 binary fast hop
        # is numeric-only; _dense_instances already rejects U/object
        # dtypes, so pass the list through unchanged).
        return await self._proxied_predict(batch)

    async def explain(self, request: Any) -> Any:
        if self.search is None:
            raise InvalidInput(f"explainer {self.name} not loaded")
        instances = v1.get_instances(request)
        if not instances:
            raise InvalidInput("anchor text needs one instance")
        text = instances[0]
        if isinstance(text, (list, tuple)):
            # Some clients pre-tokenize; the reference's contract is
            # inputs[0] = the document (anchor_text.py:51).
            text = " ".join(str(t) for t in text)
        explanation = await self.search.explain(
            str(text),
            threshold=float(self.config.get("precision_threshold",
                                            0.95)),
            batch_size=int(self.config.get("batch_size", 64)),
            beam_size=int(self.config.get("beam_size", 2)),
            max_anchor_size=(None if self.config.get("max_anchor_size")
                             is None
                             else int(self.config["max_anchor_size"])))
        return {
            "meta": {"name": "AnchorText"},
            "data": explanation,
        }
