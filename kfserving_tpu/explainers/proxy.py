"""Shared predictor-proxy base for black-box explainers.

Every reference explainer deployment (alibi/aix/art) wraps the same
shape: a Model on `:explain` whose inner model calls proxy to the
predictor over HTTP (reference alibiexplainer/explainer.py:66-76,
aixserver/model.py:44-50, artserver/model.py:43-50).  The proxy hands
`Model.predict` an ndarray payload so dense perturbation batches take
the V2 binary wire to the predictor when it speaks it (model.py
_dense_instances) instead of JSON-encoding megabytes of floats per
batch.
"""

import inspect
import json
import os
from typing import Any, Callable, Dict, Optional

import numpy as np

from kfserving_tpu.model.model import Model
from kfserving_tpu.protocol.errors import InvalidInput


class PredictorProxyModel(Model):
    """Model base with a `_proxied_predict(batch)` that calls either an
    injected predict_fn (in-process tests) or the predictor_host."""

    def __init__(self, name: str,
                 predictor_host: Optional[str] = None,
                 predict_fn: Optional[Callable] = None):
        super().__init__(name)
        self.predictor_host = predictor_host
        self._predict_fn = predict_fn

    def _load_artifact_dir(self, model_dir: str, config_filename: str):
        """Download the explainer artifact dir (when configured) and
        read its optional JSON config.  Returns (local_dir | None,
        config dict)."""
        if not model_dir:
            return None, {}
        from kfserving_tpu.storage import Storage

        local = Storage.download(model_dir)
        path = os.path.join(local, config_filename)
        if not os.path.exists(path):
            return local, {}
        with open(path) as f:
            try:
                return local, json.load(f)
            except ValueError as e:
                raise InvalidInput(
                    f"malformed explainer config {config_filename}: {e}")

    async def _proxied_predict(self, batch: np.ndarray) -> np.ndarray:
        if self._predict_fn is not None:
            out = self._predict_fn(batch)
            if inspect.isawaitable(out):
                out = await out
            return np.asarray(out)
        if not self.predictor_host:
            raise InvalidInput(
                f"explainer {self.name} has no predictor_host")
        resp = await super().predict(
            {"instances": np.asarray(batch)})
        if "predictions" not in resp:
            raise InvalidInput(
                "predictor response has no 'predictions' key")
        return np.asarray(resp["predictions"])
