"""Fairness metrics explainer — the aiffairness server's contract
(reference python/aiffairness/aifserver/model.py:25-90) without the
AIF360 dependency: the reported metrics are closed-form statistics of
(features, predictions), computed here with numpy.

explain() takes V1 instances plus either precomputed "outputs" or a
predictor_host to score against, and returns the reference's metric
dict: base_rate, consistency, disparate_impact, num_instances,
num_negatives, num_positives, statistical_parity_difference.
"""

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from kfserving_tpu.model.model import Model
from kfserving_tpu.protocol.errors import InvalidInput


class FairnessExplainer(Model):
    """Bias metrics over a batch of predictions.

    privileged_groups / unprivileged_groups: lists of {feature: value}
    conditions (a row belongs to a group when all its conditions hold),
    same shape as the reference's ctor args.
    """

    def __init__(self, name: str,
                 feature_names: Sequence[str],
                 privileged_groups: List[Dict[str, Any]],
                 unprivileged_groups: List[Dict[str, Any]],
                 favorable_label: float = 1.0,
                 unfavorable_label: float = 0.0,
                 predictor_host: Optional[str] = None,
                 n_neighbors: int = 5):
        super().__init__(name)
        self.feature_names = list(feature_names)
        self.privileged_groups = privileged_groups
        self.unprivileged_groups = unprivileged_groups
        self.favorable_label = favorable_label
        self.unfavorable_label = unfavorable_label
        self.predictor_host = predictor_host
        self.n_neighbors = n_neighbors
        self.ready = True

    def _group_mask(self, X: np.ndarray,
                    groups: List[Dict[str, Any]]) -> np.ndarray:
        """Rows matching ANY group (conditions within a group AND)."""
        mask = np.zeros(X.shape[0], dtype=bool)
        for group in groups:
            g = np.ones(X.shape[0], dtype=bool)
            for feature, value in group.items():
                try:
                    col = self.feature_names.index(feature)
                except ValueError:
                    raise InvalidInput(
                        f"group condition references unknown feature "
                        f"{feature!r}; features: {self.feature_names}")
                g &= X[:, col] == value
            mask |= g
        return mask

    def _consistency(self, X: np.ndarray, y: np.ndarray) -> float:
        """AIF360 consistency: 1 - mean |y_i - mean(y of i's kNN)|
        (k nearest rows by euclidean distance, excluding self)."""
        n = X.shape[0]
        k = min(self.n_neighbors, n - 1)
        if k <= 0:
            return 1.0
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        np.fill_diagonal(d2, np.inf)
        idx = np.argpartition(d2, k - 1, axis=1)[:, :k]
        return float(1.0 - np.abs(y - y[idx].mean(axis=1)).mean())

    def metrics(self, X: np.ndarray, preds: np.ndarray) -> Dict[str, Any]:
        favorable = preds == self.favorable_label
        priv = self._group_mask(X, self.privileged_groups)
        unpriv = self._group_mask(X, self.unprivileged_groups)

        def base_rate(mask=None) -> float:
            sel = favorable if mask is None else favorable[mask]
            return float(sel.mean()) if sel.size else 0.0

        rate_priv = base_rate(priv)
        rate_unpriv = base_rate(unpriv)
        return {
            "base_rate": base_rate(),
            "consistency": [self._consistency(
                np.asarray(X, np.float64), favorable.astype(np.float64))],
            "disparate_impact": (rate_unpriv / rate_priv
                                 if rate_priv > 0 else float("inf")),
            "num_instances": float(preds.shape[0]),
            "num_negatives": float((~favorable).sum()),
            "num_positives": float(favorable.sum()),
            "statistical_parity_difference": rate_unpriv - rate_priv,
        }

    async def explain(self, request: Any) -> Any:
        if not isinstance(request, dict) or "instances" not in request:
            raise InvalidInput('expected "instances"')
        X = np.asarray(request["instances"], dtype=np.float64)
        if X.ndim != 2 or X.shape[1] != len(self.feature_names):
            raise InvalidInput(
                f"instances must be [n, {len(self.feature_names)}] rows "
                f"matching feature_names")
        if "outputs" in request:
            preds = np.asarray(request["outputs"], dtype=np.float64)
        elif self.predictor_host:
            resp = await super().predict(
                {"instances": request["instances"]})
            preds = np.asarray(resp["predictions"], dtype=np.float64)
        else:
            raise InvalidInput(
                'request needs "outputs" (precomputed predictions) or '
                'the explainer a predictor_host')
        preds = preds.reshape(-1)
        if preds.shape[0] != X.shape[0]:
            raise InvalidInput("outputs/instances length mismatch")
        return {"predictions": preds.tolist(),
                "metrics": self.metrics(X, preds)}
