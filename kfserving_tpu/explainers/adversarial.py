"""Native adversarial-robustness explainer (artexplainer parity).

The reference serves ART's SquareAttack behind `:explain` (reference
python/artexplainer/artserver/model.py:25-77): a black-box evasion
attack that perturbs random squares of the input until the predictor's
label flips, reporting the adversarial example and its L2 distance as a
robustness certificate.  This is a first-party implementation of the
same decision-based attack (Andriushchenko et al. 2020, "Square
Attack", the p-schedule simplified) with no art dependency:

- label-only feedback, exactly like the reference's BlackBoxClassifier
  wrapper (its _predict one-hots the predicted label,
  artserver/model.py:43-50) — probabilities are used when the
  predictor returns them, improving acceptance from margin descent;
- candidate perturbations are evaluated in predictor BATCHES (one
  call per iteration of candidates), riding the dynamic batcher.

Response contract matches the reference handler: {"explanations":
{"adversarial_example", "L2 error", "adversarial_prediction",
"prediction"}} (artserver/model.py:71-74).
"""

import inspect
import json
import logging
from typing import Any, Callable, Dict, Optional

import numpy as np

from kfserving_tpu.explainers.proxy import PredictorProxyModel
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InvalidInput

logger = logging.getLogger("kfserving_tpu.explainers.adversarial")


class SquareAttack:
    """Decision/score-based square attack on one instance.

    predict_fn: batch [n, ...] -> labels [n] or probabilities [n, k].
    eps: L-inf perturbation budget (in input units).
    """

    def __init__(self, predict_fn: Callable, eps: float = 0.3,
                 max_iter: int = 100, candidates_per_iter: int = 8,
                 p_init: float = 0.3, seed: int = 0,
                 clip_min: Optional[float] = None,
                 clip_max: Optional[float] = None):
        self.predict_fn = predict_fn
        self.eps = eps
        self.max_iter = max_iter
        self.candidates = candidates_per_iter
        self.p_init = p_init
        self.clip_min = clip_min
        self.clip_max = clip_max
        self.rng = np.random.default_rng(seed)
        # One-hot width for label-only predictors: at least the target
        # label + 1 (set by attack()) and monotone over everything
        # observed, so scores[:, label] always exists even when a batch
        # happens not to contain the high classes.
        self._n_classes = 2

    async def _scores(self, batch: np.ndarray) -> np.ndarray:
        """[n, k] scores; label outputs become one-hot (the reference's
        BlackBoxClassifier sees exactly that)."""
        out = self.predict_fn(batch)
        if inspect.isawaitable(out):
            out = await out
        out = np.asarray(out)
        if out.ndim == 1:
            self._n_classes = max(self._n_classes, int(out.max()) + 1)
            return np.eye(self._n_classes)[out.astype(np.int64)]
        return np.asarray(out, np.float64)

    def _margin(self, scores: np.ndarray, label: int) -> np.ndarray:
        """score[label] - best other; < 0 means misclassified."""
        if label >= scores.shape[1]:
            raise InvalidInput(
                f"label {label} out of range for predictor with "
                f"{scores.shape[1]} classes")
        others = scores.copy()
        others[:, label] = -np.inf
        return scores[:, label] - others.max(axis=1)

    def _square(self, shape, p: float):
        """Random square's slice bounds at side = sqrt(p * H * W)."""
        h, w = shape[0], shape[1]
        side = max(1, int(round((p * h * w) ** 0.5)))
        side = min(side, h, w)
        r = int(self.rng.integers(0, h - side + 1))
        c = int(self.rng.integers(0, w - side + 1))
        return slice(r, r + side), slice(c, c + side)

    async def attack(self, x: np.ndarray, label: int) -> Dict[str, Any]:
        x = np.asarray(x, np.float64)
        self._n_classes = max(self._n_classes, label + 1)
        if x.ndim == 1:
            # Tabular rows attack as [1, d] "images".
            work = x[None, :, None]
        elif x.ndim == 2:
            work = x[..., None]
        else:
            work = x
        # Unclipped by default, like the reference's BlackBoxClassifier
        # clip_values=(-inf, inf) (artserver/model.py:65); domains with
        # real bounds set them in art.json.
        clip_min = self.clip_min if self.clip_min is not None \
            else -np.inf
        clip_max = self.clip_max if self.clip_max is not None \
            else np.inf

        base_scores = await self._scores(x[None])
        prediction = int(np.argmax(base_scores[0]))
        best = work.copy()
        best_margin = float(self._margin(base_scores, label)[0])
        queries = 1
        for it in range(self.max_iter):
            if best_margin < 0:
                break  # already adversarial
            # Square side shrinks as the attack progresses (the paper's
            # p-schedule, piecewise-halved: p_init for the first fifth
            # of the budget, p_init/2 for the second, ...).
            p = self.p_init * 2.0 ** (
                -((it * 5) // max(1, self.max_iter)))
            batch = np.stack([best] * self.candidates)
            for b in range(self.candidates):
                rs, cs = self._square(work.shape, p)
                delta = self.rng.choice([-self.eps, self.eps],
                                        size=(1, 1, work.shape[2]))
                batch[b][rs, cs, :] = np.clip(
                    work[rs, cs, :] + delta, clip_min, clip_max)
            scores = await self._scores(
                batch.reshape((self.candidates,) + x.shape))
            queries += 1
            margins = self._margin(scores, label)
            j = int(np.argmin(margins))
            if margins[j] < best_margin:
                best = batch[j]
                best_margin = float(margins[j])
        adv = best.reshape(x.shape)
        adv_scores = await self._scores(adv[None])
        return {
            "adversarial_example": adv.tolist(),
            "L2 error": float(np.linalg.norm((adv - x).ravel())),
            "adversarial_prediction": int(np.argmax(adv_scores[0])),
            "prediction": prediction,
            "success": bool(best_margin < 0),
            "queries": queries,
        }


class AdversarialRobustness(PredictorProxyModel):
    """Served square-attack explainer (`:explain`, predictor proxy —
    the artexplainer deployment shape, artserver/model.py:43-50).

    Artifact layout (`storage_uri`, all optional):
        art.json — {"eps": 0.3, "max_iter": 100, "clip_min": 0.0,
                    "clip_max": 1.0, "candidates_per_iter": 8}
    """

    def __init__(self, name: str, model_dir: str = "",
                 predictor_host: Optional[str] = None,
                 predict_fn: Optional[Callable] = None):
        super().__init__(name, predictor_host=predictor_host,
                         predict_fn=predict_fn)
        self.model_dir = model_dir
        self.config: Dict[str, Any] = {}

    def load(self) -> bool:
        _, self.config = self._load_artifact_dir(self.model_dir,
                                                 "art.json")
        self.ready = True
        return True

    async def explain(self, request: Any) -> Any:
        # Reference contract: instances = [image, label]
        # (artserver/model.py:53-54).
        instances = v1.get_instances(request)
        if len(instances) < 2:
            raise InvalidInput(
                "adversarial explainer needs instances = [input, label]")
        x = np.asarray(instances[0], np.float64)
        label = int(np.asarray(instances[1]).reshape(-1)[0])
        req = request if isinstance(request, dict) else {}
        attack = SquareAttack(
            self._proxied_predict,
            eps=float(req.get("eps", self.config.get("eps", 0.3))),
            max_iter=int(req.get(
                "max_iter", self.config.get("max_iter", 100))),
            candidates_per_iter=int(self.config.get(
                "candidates_per_iter", 8)),
            clip_min=self.config.get("clip_min"),
            clip_max=self.config.get("clip_max"),
            seed=int(self.config.get("seed", 0)))
        return {"explanations": await attack.attack(x, label)}
