"""Gradient-saliency explainer for JAX models.

explain() returns, per instance, the input-gradient attribution of the
winning logit: d logit[argmax] / d input, reduced over non-feature axes.
Runs as one jitted program on the same device as the model — contrast the
reference's explainer pods, which POST thousands of perturbed samples to
the predictor over HTTP (reference alibiexplainer/explainer.py:39-100).

Serves either:
- co-located: constructed over a loaded JaxModel's spec/params; or
- standalone explainer pod: constructed with its own model_dir copy
  (the reference's explainer downloads the same storageUri).
"""

import logging
from typing import Any, Dict, Optional

import numpy as np

from kfserving_tpu.predictors.jax_model import JaxModel
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InferenceError

logger = logging.getLogger("kfserving_tpu.explainers")


class SaliencyExplainer(JaxModel):
    """JaxModel whose explain() returns input-gradient saliency maps."""

    def __init__(self, name: str, model_dir: str, **kwargs):
        super().__init__(name, model_dir, **kwargs)
        self._saliency_fn = None

    def load(self) -> bool:
        ok = super().load()
        if not ok:
            return ok
        import jax
        import jax.numpy as jnp

        engine = self.engine
        params = engine.params
        base = engine._jitted  # serve_fn(params, batch)

        def winning_logit_sum(x):
            out = base(params, x)
            # output modes: logits [B, C] (or [B, L, C]); reduce to the
            # winning class per instance and sum over batch for one grad.
            logits = out if not isinstance(out, dict) else out["values"]
            winners = jnp.max(logits, axis=-1)
            return jnp.sum(winners)

        self._saliency_fn = jax.jit(jax.grad(winning_logit_sum))
        return ok

    async def explain(self, request: Any) -> Any:
        if self.predictor_host:
            return await super().explain(request)
        if self._saliency_fn is None:
            raise InferenceError(f"explainer {self.name} not loaded")
        instances = v1.get_instances(request)
        batch = np.asarray(instances, dtype=np.float32)
        import asyncio

        loop = asyncio.get_running_loop()
        grads = await loop.run_in_executor(
            None, lambda: np.asarray(self._saliency_fn(batch)))
        return {
            "explanations": [
                {"saliency": g.tolist(),
                 "method": "gradient_saliency"} for g in grads
            ]
        }

    def metadata(self) -> Dict[str, Any]:
        meta = super().metadata()
        meta["explainer"] = "gradient_saliency"
        return meta


class BlackBoxExplainer(JaxModel):
    """Parity shape with the reference explainer pods: explain() perturbs
    inputs locally and scores them against predictor_host over HTTP
    (reference explainer_wrapper.py _predict_fn pattern).  Feature
    importance = prediction flip rate under feature masking."""

    def __init__(self, name: str, num_samples: int = 32,
                 seed: int = 0):
        # Deliberately not calling JaxModel.__init__ loading machinery:
        # black-box explainers own no model artifact.
        from kfserving_tpu.model.model import Model

        Model.__init__(self, name)
        self.num_samples = num_samples
        self.seed = seed

    def load(self) -> bool:
        self.ready = True
        return True

    async def explain(self, request: Any) -> Any:
        if not self.predictor_host:
            raise InferenceError(
                "BlackBoxExplainer requires predictor_host")
        instances = v1.get_instances(request)
        batch = np.asarray(instances, dtype=np.float32)
        base = await self._remote_predict(batch)
        rng = np.random.default_rng(self.seed)
        n_features = batch.shape[1]
        importance = np.zeros((batch.shape[0], n_features))
        for f in range(n_features):
            flips = np.zeros(batch.shape[0])
            for _ in range(self.num_samples):
                perturbed = batch.copy()
                perturbed[:, f] = rng.permutation(perturbed[:, f])
                pred = await self._remote_predict(perturbed)
                flips += (np.asarray(pred) != np.asarray(base)).reshape(
                    batch.shape[0], -1).any(axis=1)
            importance[:, f] = flips / self.num_samples
        return {"explanations": [
            {"feature_importance": imp.tolist(),
             "method": "permutation_flip_rate"} for imp in importance]}

    async def _remote_predict(self, batch: np.ndarray):
        from kfserving_tpu.model.model import PREDICTOR_URL_FORMAT

        url = PREDICTOR_URL_FORMAT.format(self.predictor_host, self.name)
        resp = await self._proxy(url, {"instances": batch.tolist()})
        return resp["predictions"]
