"""Gradient-saliency explainer for JAX models.

explain() returns, per instance, the input-gradient attribution of the
winning logit: d logit[argmax] / d input, reduced over non-feature axes.
Runs as one jitted program on the same device as the model — contrast the
reference's explainer pods, which POST thousands of perturbed samples to
the predictor over HTTP (reference alibiexplainer/explainer.py:39-100).

Deployment shapes (reference ingress splits :explain to the explainer,
ingress_reconciler.go:219+):
- SaliencyExplainer: downloads the same storageUri as the predictor and
  differentiates through the model locally (white box).
- BlackBoxExplainer: owns no artifact; perturbs inputs and scores them
  against predictor_host over HTTP (the reference explainer shape).
"""

import logging
from typing import Any, Dict

import numpy as np

from kfserving_tpu.explainers.proxy import PredictorProxyModel
from kfserving_tpu.predictors.jax_model import JaxModel
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InferenceError

logger = logging.getLogger("kfserving_tpu.explainers")


class SaliencyExplainer(JaxModel):
    """JaxModel whose explain() returns input-gradient saliency maps.

    Differentiates through the raw logits (`_base_apply`), not the serving
    output mode — argmax/topk-configured models explain identically."""

    def __init__(self, name: str, model_dir: str, **kwargs):
        super().__init__(name, model_dir, **kwargs)
        self._saliency_fn = None

    def load(self) -> bool:
        ok = super().load()
        if not ok:
            return ok
        import jax
        import jax.numpy as jnp

        params = self.engine.params
        base_apply = self._base_apply
        scale = self.config.scale

        def winning_logit_sum(x):
            if scale is not None:
                x = x * scale  # same on-device input scaling as serving
            logits = base_apply(params, x)
            winners = jnp.max(logits, axis=-1)
            return jnp.sum(winners)

        self._saliency_fn = jax.jit(jax.grad(winning_logit_sum))
        return ok

    async def explain(self, request: Any) -> Any:
        if self._saliency_fn is None:
            raise InferenceError(f"explainer {self.name} not loaded")
        instances = v1.get_instances(request)
        batch = np.asarray(instances, dtype=np.float32)
        import asyncio

        loop = asyncio.get_running_loop()
        grads = await loop.run_in_executor(
            None, lambda: np.asarray(self._saliency_fn(batch)))
        return {
            "explanations": [
                {"saliency": g.tolist(),
                 "method": "gradient_saliency"} for g in grads
            ]
        }

    def metadata(self) -> Dict[str, Any]:
        meta = super().metadata()
        meta["explainer"] = "gradient_saliency"
        return meta


class BlackBoxExplainer(PredictorProxyModel):
    """Parity shape with the reference explainer pods: explain() perturbs
    inputs locally and scores them against predictor_host over HTTP
    (reference explainer_wrapper.py _predict_fn pattern).  Feature
    importance = prediction flip rate under Gaussian feature jitter
    (noise-based so single-instance requests perturb too)."""

    def __init__(self, name: str, num_samples: int = 32,
                 noise_scale: float = 1.0, seed: int = 0,
                 predict_fn=None):
        super().__init__(name, predict_fn=predict_fn)
        self.num_samples = num_samples
        self.noise_scale = noise_scale
        self.seed = seed

    def load(self) -> bool:
        self.ready = True
        return True

    async def explain(self, request: Any) -> Any:
        if not self.predictor_host and self._predict_fn is None:
            raise InferenceError(
                "BlackBoxExplainer requires predictor_host")
        instances = v1.get_instances(request)
        batch = np.asarray(instances, dtype=np.float32)
        base = await self._remote_predict(batch)
        rng = np.random.default_rng(self.seed)
        n_features = batch.shape[1]
        # Perturbation scale per feature: column std across the batch when
        # informative, else noise_scale (handles batch == 1).
        stds = batch.std(axis=0)
        stds = np.where(stds > 0, stds, self.noise_scale)
        importance = np.zeros((batch.shape[0], n_features))
        for f in range(n_features):
            flips = np.zeros(batch.shape[0])
            for _ in range(self.num_samples):
                perturbed = batch.copy()
                perturbed[:, f] += rng.normal(
                    0.0, stds[f], size=batch.shape[0])
                pred = await self._remote_predict(perturbed)
                flips += (np.asarray(pred) != np.asarray(base)).reshape(
                    batch.shape[0], -1).any(axis=1)
            importance[:, f] = flips / self.num_samples
        return {"explanations": [
            {"feature_importance": imp.tolist(),
             "method": "noise_flip_rate"} for imp in importance]}

    def metadata(self) -> Dict[str, Any]:
        meta = super().metadata()
        meta["explainer"] = "noise_flip_rate"
        return meta

    async def _remote_predict(self, batch: np.ndarray):
        # Shared proxy hop (ndarray payload -> V2 binary wire when the
        # predictor speaks it, clean error on a malformed response);
        # kept as a named method because tests monkeypatch it.
        return await self._proxied_predict(batch)
