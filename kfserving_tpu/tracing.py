"""Request tracing: span IDs through router -> server -> engine.

The reference delegates distributed tracing to the Istio/Knative mesh
(queue-proxy emits request traces, reference test/benchmark/
README.md:5-12); the TPU build is sidecar-free, so SURVEY §5.1 calls
for its own spans plus `jax.profiler` hooks around compile/execute.

Design: a process-wide ring buffer of completed spans plus a
contextvar carrying the current request id.  The request id enters at
the ingress router (or is minted at the server) via the
``x-request-id`` header, rides the contextvar through the asyncio
handler and — via ``contextvars.copy_context`` — into the engine's
worker threads, so engine sub-spans (prepare/transfer/compute/fetch)
attach to the request that caused them.  Spans are queryable at
``GET /debug/traces`` and logged at DEBUG.

The `jax.profiler` toggle (``POST /debug/profiler/start|stop``) wraps
``jax.profiler.start_trace`` for on-demand XLA-level traces.
"""

import contextlib
import contextvars
import logging
import re
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("kfserving_tpu.tracing")

REQUEST_ID_HEADER = "x-request-id"
# W3C Trace Context (https://www.w3.org/TR/trace-context/): the
# cross-hop carrier.  `traceparent` wins over x-request-id when both
# arrive; x-request-id stays the echo/correlation header for clients
# that never adopted W3C.
TRACEPARENT_HEADER = "traceparent"

_HEX32 = re.compile(r"^[0-9a-f]{32}$")
_HEX16 = re.compile(r"^[0-9a-f]{16}$")

# Current request id; propagated into engine worker threads by running
# the executor callable under contextvars.copy_context().
current_request_id: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("kfs_request_id", default=None)


def mint_trace_id() -> str:
    return uuid.uuid4().hex


def mint_span_id() -> str:
    return uuid.uuid4().hex[:16]


def parse_traceparent(value: str) -> Optional[Tuple[str, str]]:
    """(trace_id, parent_span_id) from a `traceparent` header, or None
    when malformed (all-zero ids are invalid per spec)."""
    parts = value.strip().lower().split("-")
    if len(parts) < 4:
        return None
    _version, trace_id, span_id = parts[0], parts[1], parts[2]
    if not _HEX32.match(trace_id) or not _HEX16.match(span_id):
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


@dataclass
class TraceContext:
    """One hop's view of the request's trace: the shared trace id, the
    upstream hop's span id (None at the trace root), and this hop's
    own span id (forwarded downstream as the parent)."""

    trace_id: str
    parent_span_id: Optional[str] = None
    span_id: str = field(default_factory=mint_span_id)

    def forward_traceparent(self) -> Optional[str]:
        """The `traceparent` value to send downstream, or None when
        the trace id is not W3C-shaped (a client-supplied
        x-request-id keeps carrying context on its own header — never
        rewrite the id the client correlates by)."""
        if not _HEX32.match(self.trace_id):
            return None
        return format_traceparent(self.trace_id, self.span_id)


def ensure_trace_context(headers: Dict[str, str],
                         mint: str = "short") -> TraceContext:
    """Join (or start) the request's trace and set the contextvar.

    Precedence: a valid `traceparent` wins (its 32-hex trace id
    becomes THE id on every layer's spans); else `x-request-id` (any
    string — legacy correlation); else a fresh id is minted.
    ``mint="w3c"`` mints a full 32-hex id (the ingress router, which
    must emit a valid traceparent); ``"short"`` keeps the seed's
    16-hex x-request-id shape (replica-local minting)."""
    tp = headers.get(TRACEPARENT_HEADER)
    if tp:
        parsed = parse_traceparent(tp)
        if parsed is not None:
            ctx = TraceContext(parsed[0], parent_span_id=parsed[1])
            current_request_id.set(ctx.trace_id)
            return ctx
    rid = headers.get(REQUEST_ID_HEADER)
    if not rid:
        rid = mint_trace_id() if mint == "w3c" else uuid.uuid4().hex[:16]
    ctx = TraceContext(rid)
    current_request_id.set(ctx.trace_id)
    return ctx


@dataclass
class Span:
    trace_id: str
    name: str
    start: float          # time.time() epoch seconds
    duration_ms: float
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "name": self.name,
                "start": self.start, "duration_ms": self.duration_ms,
                "attrs": self.attrs}


class Tracer:
    """Process-wide completed-span ring buffer (bounded, lock-guarded)."""

    def __init__(self, capacity: int = 512):
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.enabled = True

    def record(self, span: Span) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._spans.append(span)
        logger.debug("span %s %s %.2fms %s", span.trace_id, span.name,
                     span.duration_ms, span.attrs)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Time a block; attaches to the current request id (or 'untraced').
        Yields a dict the block may add attributes to."""
        trace_id = current_request_id.get() or "untraced"
        start_wall = time.time()
        start = time.perf_counter()
        span_attrs: Dict[str, Any] = dict(attrs)
        try:
            yield span_attrs
        finally:
            self.record(Span(trace_id, name, start_wall,
                             (time.perf_counter() - start) * 1000.0,
                             span_attrs))

    def spans(self, trace_id: Optional[str] = None,
              limit: int = 100) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._spans)
        if trace_id is not None:
            items = [s for s in items if s.trace_id == trace_id]
        return [s.to_dict() for s in items[-limit:]]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


# The process tracer (one serving process = one trace sink).
tracer = Tracer()


def ensure_request_id(headers: Dict[str, str]) -> str:
    """Read (or mint) the request id for an incoming request and set
    the contextvar.  Returns the id so responses can echo it.  Joins a
    W3C trace when the request carries one (ensure_trace_context)."""
    return ensure_trace_context(headers).trace_id


class ProfilerControl:
    """On-demand jax.profiler trace capture (SURVEY §5.1)."""

    def __init__(self):
        self._active_dir: Optional[str] = None
        self._lock = threading.Lock()

    @property
    def active_dir(self) -> Optional[str]:
        return self._active_dir

    def start(self, log_dir: str) -> bool:
        import jax

        with self._lock:
            if self._active_dir is not None:
                return False
            jax.profiler.start_trace(log_dir)
            self._active_dir = log_dir
            logger.info("jax.profiler trace -> %s", log_dir)
            return True

    def stop(self) -> Optional[str]:
        import jax

        with self._lock:
            if self._active_dir is None:
                return None
            jax.profiler.stop_trace()
            out, self._active_dir = self._active_dir, None
            logger.info("jax.profiler trace stopped (%s)", out)
            return out


profiler = ProfilerControl()
