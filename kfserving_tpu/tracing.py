"""Request tracing: span IDs through router -> server -> engine.

The reference delegates distributed tracing to the Istio/Knative mesh
(queue-proxy emits request traces, reference test/benchmark/
README.md:5-12); the TPU build is sidecar-free, so SURVEY §5.1 calls
for its own spans plus `jax.profiler` hooks around compile/execute.

Design: a process-wide ring buffer of completed spans plus a
contextvar carrying the current request id.  The request id enters at
the ingress router (or is minted at the server) via the
``x-request-id`` header, rides the contextvar through the asyncio
handler and — via ``contextvars.copy_context`` — into the engine's
worker threads, so engine sub-spans (prepare/transfer/compute/fetch)
attach to the request that caused them.  Spans are queryable at
``GET /debug/traces`` and logged at DEBUG.

The `jax.profiler` toggle (``POST /debug/profiler/start|stop``) wraps
``jax.profiler.start_trace`` for on-demand XLA-level traces.
"""

import contextlib
import contextvars
import logging
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger("kfserving_tpu.tracing")

REQUEST_ID_HEADER = "x-request-id"

# Current request id; propagated into engine worker threads by running
# the executor callable under contextvars.copy_context().
current_request_id: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("kfs_request_id", default=None)


@dataclass
class Span:
    trace_id: str
    name: str
    start: float          # time.time() epoch seconds
    duration_ms: float
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "name": self.name,
                "start": self.start, "duration_ms": self.duration_ms,
                "attrs": self.attrs}


class Tracer:
    """Process-wide completed-span ring buffer (bounded, lock-guarded)."""

    def __init__(self, capacity: int = 512):
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.enabled = True

    def record(self, span: Span) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._spans.append(span)
        logger.debug("span %s %s %.2fms %s", span.trace_id, span.name,
                     span.duration_ms, span.attrs)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Time a block; attaches to the current request id (or 'untraced').
        Yields a dict the block may add attributes to."""
        trace_id = current_request_id.get() or "untraced"
        start_wall = time.time()
        start = time.perf_counter()
        span_attrs: Dict[str, Any] = dict(attrs)
        try:
            yield span_attrs
        finally:
            self.record(Span(trace_id, name, start_wall,
                             (time.perf_counter() - start) * 1000.0,
                             span_attrs))

    def spans(self, trace_id: Optional[str] = None,
              limit: int = 100) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._spans)
        if trace_id is not None:
            items = [s for s in items if s.trace_id == trace_id]
        return [s.to_dict() for s in items[-limit:]]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


# The process tracer (one serving process = one trace sink).
tracer = Tracer()


def ensure_request_id(headers: Dict[str, str]) -> str:
    """Read (or mint) the request id for an incoming request and set the
    contextvar.  Returns the id so responses can echo it."""
    rid = headers.get(REQUEST_ID_HEADER) or uuid.uuid4().hex[:16]
    current_request_id.set(rid)
    return rid


class ProfilerControl:
    """On-demand jax.profiler trace capture (SURVEY §5.1)."""

    def __init__(self):
        self._active_dir: Optional[str] = None
        self._lock = threading.Lock()

    @property
    def active_dir(self) -> Optional[str]:
        return self._active_dir

    def start(self, log_dir: str) -> bool:
        import jax

        with self._lock:
            if self._active_dir is not None:
                return False
            jax.profiler.start_trace(log_dir)
            self._active_dir = log_dir
            logger.info("jax.profiler trace -> %s", log_dir)
            return True

    def stop(self) -> Optional[str]:
        import jax

        with self._lock:
            if self._active_dir is None:
                return None
            jax.profiler.stop_trace()
            out, self._active_dir = self._active_dir, None
            logger.info("jax.profiler trace stopped (%s)", out)
            return out


profiler = ProfilerControl()
