"""Client-side credential registration (SDK creds_utils analogue).

The reference SDK ships `set_gcs_credentials` / `set_s3_credentials` /
`set_azure_credentials` helpers that read local credential files and
create the Secret + ServiceAccount objects the control plane's
credential builder consumes (reference
python/kfserving/kfserving/api/creds_utils.py:26-142).  These helpers do
the same against the control API's /v1/secrets surface: parse the file
client-side, ship only the needed fields, attach to a service account.

File formats match the reference exactly:

- GCS: the service-account JSON key file, shipped verbatim.
- S3: an AWS-CLI credentials file (INI with aws_access_key_id /
  aws_secret_access_key under a profile, creds_utils.py:69-75).
- Azure: the `az ad sp create-for-rbac --sdk-auth` JSON with
  clientId/clientSecret/subscriptionId/tenantId (creds_utils.py:126-134).
"""

import configparser
import json
from os.path import expanduser
from typing import Any, Dict, Optional

from kfserving_tpu.storage.credentials import (
    S3_ENDPOINT_ANNOTATION,
    S3_REGION_ANNOTATION,
    S3_USEHTTPS_ANNOTATION,
    S3_VERIFYSSL_ANNOTATION,
)


def gcs_secret_payload(credentials_file: str) -> Dict[str, Any]:
    with open(expanduser(credentials_file)) as f:
        content = f.read()
    # Keep the key file verbatim (the builder writes it back to disk for
    # GOOGLE_APPLICATION_CREDENTIALS); validate it parses so a wrong path
    # fails here, not at model-pull time.
    json.loads(content)
    return {"type": "gcs", "data": {"gcloud": content}}


def s3_secret_payload(credentials_file: str, s3_profile: str = "default",
                      s3_endpoint: Optional[str] = None,
                      s3_region: Optional[str] = None,
                      s3_use_https: Optional[str] = None,
                      s3_verify_ssl: Optional[str] = None
                      ) -> Dict[str, Any]:
    config = configparser.ConfigParser()
    config.read([expanduser(credentials_file)])
    try:
        payload: Dict[str, Any] = {
            "type": "s3",
            "data": {
                "accessKeyId": config.get(s3_profile,
                                          "aws_access_key_id"),
                "secretAccessKey": config.get(s3_profile,
                                              "aws_secret_access_key"),
            },
        }
    except configparser.Error as e:
        # Fail early with the file+profile named, matching the gcs
        # payload's validation, instead of a raw configparser traceback
        # from the CLI.
        raise ValueError(
            f"profile {s3_profile!r} with aws_access_key_id/"
            f"aws_secret_access_key not found in "
            f"{credentials_file}: {e}") from e
    annotations = {}
    for value, key in ((s3_endpoint, S3_ENDPOINT_ANNOTATION),
                       (s3_region, S3_REGION_ANNOTATION),
                       (s3_use_https, S3_USEHTTPS_ANNOTATION),
                       (s3_verify_ssl, S3_VERIFYSSL_ANNOTATION)):
        if value is not None:
            annotations[key] = str(value)
    if annotations:
        payload["annotations"] = annotations
    return payload


def azure_secret_payload(credentials_file: str) -> Dict[str, Any]:
    with open(expanduser(credentials_file)) as f:
        azure_creds = json.load(f)
    return {
        "type": "azure",
        "data": {
            "clientId": azure_creds["clientId"],
            "clientSecret": azure_creds["clientSecret"],
            "subscriptionId": azure_creds["subscriptionId"],
            "tenantId": azure_creds["tenantId"],
        },
    }
