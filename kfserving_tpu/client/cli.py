"""kfs CLI: kubectl-style command line over the control API.

    python -m kfserving_tpu.client apply -f isvc.json
    python -m kfserving_tpu.client get [NAME]
    python -m kfserving_tpu.client delete NAME
    python -m kfserving_tpu.client wait NAME --timeout 120
    python -m kfserving_tpu.client predict NAME -d '{"instances": [[...]]}'
    python -m kfserving_tpu.client canary NAME --percent 20
    python -m kfserving_tpu.client promote NAME
    python -m kfserving_tpu.client rollouts
    python -m kfserving_tpu.client profile --window 60 -o trace.json
    python -m kfserving_tpu.client cache [--replica HOST] [--top-k N] \
        [--top-cost N]
    python -m kfserving_tpu.client history [SERIES] [--window S] \
        [--replica HOST]
    python -m kfserving_tpu.client incidents [ID] [--state open]
    python -m kfserving_tpu.client doctor

The reference splits this between kubectl (CRDs) and the SDK; the TPU
build ships one client for both planes.
"""

import argparse
import asyncio
import json
import sys
import time

from kfserving_tpu.client.client import KFServingClient

parser = argparse.ArgumentParser(prog="kfs")
parser.add_argument("--control-url", default="http://127.0.0.1:8081")
parser.add_argument("--ingress-url", default="http://127.0.0.1:8080")
parser.add_argument("--namespace", "-n", default="default")
sub = parser.add_subparsers(dest="command", required=True)

p_apply = sub.add_parser("apply", help="create or update from a spec file")
p_apply.add_argument("-f", "--filename", required=True)

p_get = sub.add_parser("get", help="get one isvc (or list all)")
p_get.add_argument("name", nargs="?")

p_delete = sub.add_parser("delete")
p_delete.add_argument("name")

p_wait = sub.add_parser("wait", help="block until ready")
p_wait.add_argument("name")
p_wait.add_argument("--timeout", type=float, default=120.0)

p_predict = sub.add_parser("predict")
p_predict.add_argument("name")
p_predict.add_argument("-d", "--data", help="inline JSON payload")
p_predict.add_argument("-f", "--filename", help="payload file")
p_predict.add_argument("--protocol", default="v1", choices=["v1", "v2"])
p_predict.add_argument("--model", default=None,
                       help="model name when it differs from the isvc "
                            "(TrainedModel under a multi-model isvc)")

p_explain = sub.add_parser("explain")
p_explain.add_argument("name")
p_explain.add_argument("-d", "--data")
p_explain.add_argument("-f", "--filename")

p_canary = sub.add_parser("canary", help="set canary traffic percent")
p_canary.add_argument("name")
p_canary.add_argument("--percent", type=int, required=True)

p_promote = sub.add_parser("promote", help="promote canary to 100%%")
p_promote.add_argument("name")

sub.add_parser("rollouts",
               help="progressive-delivery status (active rollouts, "
                    "rollbacks with evidence, quarantine)")

p_profile = sub.add_parser(
    "profile",
    help="fetch the fleet device-time profile (engine event timeline "
         "as Chrome-trace JSON) and save it for Perfetto")
p_profile.add_argument("--window", type=float, default=None,
                       help="trailing window in seconds (default: "
                            "the whole event ring)")
p_profile.add_argument("--replica", default=None,
                       help="narrow to one replica host:port")
p_profile.add_argument("-o", "--output", default="trace.json",
                       help="file to write the trace to (load it at "
                            "ui.perfetto.dev)")

p_cache = sub.add_parser(
    "cache",
    help="fleet cache & cost snapshot (per-replica prefix-index "
         "census, hot chains, pool/HBM occupancy)")
p_cache.add_argument("--replica", default=None,
                     help="narrow to one replica host:port")
p_cache.add_argument("--top-k", type=int, default=None,
                     help="hot chains per model (default 10)")
p_cache.add_argument("--top-cost", type=int, default=None,
                     help="also list the top-N cost-attribution "
                          "records by attributed device-ms and by KV "
                          "blocks held")

p_history = sub.add_parser(
    "history",
    help="telemetry history (ring-TSDB frames) rendered as one "
         "sparkline per fleet series")
p_history.add_argument("series", nargs="?",
                       help="family name (e.g. kfserving_tpu_"
                            "request_latency_ms_p99); omit for every "
                            "live series")
p_history.add_argument("--labels", default=None,
                       help="label filter, k=v[,k2=v2...]")
p_history.add_argument("--window", type=float, default=None,
                       help="lookback seconds (default 600)")
p_history.add_argument("--step", type=float, default=None,
                       help="alignment grid seconds (default 1)")
p_history.add_argument("--replica", default=None,
                       help="narrow to one replica host:port")
p_history.add_argument("--json", action="store_true",
                       help="raw federated frames instead of "
                            "sparklines")

p_incidents = sub.add_parser(
    "incidents",
    help="diagnosed incidents (detector firings joined into "
         "evidence-bearing records with ranked causal hypotheses)")
p_incidents.add_argument("id", nargs="?",
                         help="incident id for the full record "
                              "(evidence bundle included)")
p_incidents.add_argument("--state", default=None,
                         choices=["open", "closed"],
                         help="filter the listing by state")
p_incidents.add_argument("--limit", type=int, default=None)
p_incidents.add_argument("--replica", default=None,
                         help="narrow to one replica host:port")
p_incidents.add_argument("--json", action="store_true",
                         help="raw wire body instead of the rendered "
                              "digest")

p_doctor = sub.add_parser(
    "doctor",
    help="one-shot fleet health digest: open incidents with top "
         "hypotheses, trend slopes, latency/MFU/occupancy snapshot")
p_doctor.add_argument(
    "--exit-code", action="store_true",
    help="exit nonzero (2) when any incident is open — makes the "
         "doctor scriptable as a CI / cron health gate")

p_creds = sub.add_parser(
    "credentials",
    help="register storage credentials (reference set_credentials)")
creds_sub = p_creds.add_subparsers(dest="creds_command", required=True)
for _provider in ("gcs", "s3", "azure"):
    cp = creds_sub.add_parser(f"set-{_provider}")
    cp.add_argument("-f", "--credentials-file", required=True)
    cp.add_argument("--service-account", default="default")
    if _provider == "s3":
        cp.add_argument("--profile", default="default")
        cp.add_argument("--endpoint", default=None)
        cp.add_argument("--region", default=None)
        cp.add_argument("--use-https", default=None)
        cp.add_argument("--verify-ssl", default=None)
creds_sub.add_parser("list")
creds_del = creds_sub.add_parser("delete")
creds_del.add_argument("name")

p_tm = sub.add_parser("trainedmodel", help="TrainedModel ops")
tm_sub = p_tm.add_subparsers(dest="tm_command", required=True)
tm_apply = tm_sub.add_parser("apply")
tm_apply.add_argument("-f", "--filename", required=True)
tm_delete = tm_sub.add_parser("delete")
tm_delete.add_argument("name")
tm_get = tm_sub.add_parser("get")
tm_get.add_argument("name", nargs="?")


def _payload(args) -> dict:
    if getattr(args, "data", None):
        return json.loads(args.data)
    if getattr(args, "filename", None):
        with open(args.filename) as f:
            return json.load(f)
    return json.load(sys.stdin)


async def _payload_async(args) -> dict:
    """`_payload` off the loop (kfslint async-blocking): `kfs predict
    -f -` reads stdin, which can block indefinitely on a pipe."""
    return await asyncio.get_running_loop().run_in_executor(
        None, _payload, args)


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values) -> str:
    """One unicode block character per frame, scaled to the series'
    own min..max (a flat series renders as a flat floor line)."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK[0] * len(values)
    span = hi - lo
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / span * len(_SPARK)))]
        for v in values)


def _render_history(body: dict) -> str:
    """The fleet rollup as text: one header + sparkline per series.

    Accepts both wire shapes: the router's federation (`replicas` +
    `fleet`) and a single replica's flat `series` list (pointing
    --ingress-url straight at a model server works too)."""
    lines = []
    if "series" in body and "fleet" not in body:
        lines.append("replicas: (single replica)")
        fleet = body.get("series") or []
    else:
        replicas = sorted((body.get("replicas") or {}).keys())
        lines.append(f"replicas: {', '.join(replicas) or '(none)'}")
        fleet = body.get("fleet") or []
    if not fleet:
        lines.append("(no series matched)")
    for s in fleet:
        values = [f[1] for f in (s.get("frames") or [])]
        label = ",".join(f"{k}={v}" for k, v in
                         sorted((s.get("labels") or {}).items()))
        name = s.get("name", "")
        head = f"{name}{{{label}}}" if label else name
        if values:
            head += (f"  [{s.get('kind')}] last={values[-1]:.4g} "
                     f"min={min(values):.4g} max={max(values):.4g} "
                     f"n={len(values)}")
        lines.append(head)
        lines.append("  " + (_sparkline(values) or "(no frames)"))
    return "\n".join(lines)


def _fmt_ts(ts) -> str:
    if ts is None:
        return "-"
    return time.strftime("%H:%M:%S", time.localtime(ts))


def _fmt_hyp(hyp: dict) -> str:
    """One hypothesis with its supporting numbers inline."""
    ev = hyp.get("evidence") or {}
    nums = ", ".join(f"{k}={v}" for k, v in sorted(ev.items()))
    line = (f"{hyp.get('cause')} (score {hyp.get('score', 0):.2f}): "
            f"{hyp.get('summary', '')}")
    if nums:
        line += f" [{nums}]"
    return line


def _render_incident_detail(inc: dict) -> str:
    lines = [f"incident {inc.get('id')}  [{inc.get('state')}]  "
             f"model={inc.get('model')}  root_cause="
             f"{inc.get('root_cause') or 'unclassified'}"]
    if inc.get("replica"):
        lines.append(f"replica: {inc['replica']}")
    lines.append(f"opened: {_fmt_ts(inc.get('opened_ts'))}  "
                 f"updated: {_fmt_ts(inc.get('updated_ts'))}  "
                 f"closed: {_fmt_ts(inc.get('closed_ts'))}")
    counts = inc.get("trigger_counts") or {}
    if counts:
        lines.append("triggers: " + ", ".join(
            f"{k}x{v}" for k, v in sorted(counts.items())))
    lines.append("hypotheses:")
    for hyp in inc.get("hypotheses") or []:
        lines.append("  " + _fmt_hyp(hyp))
    if not inc.get("hypotheses"):
        lines.append("  (unclassified — bundle held no usable "
                     "decomposition)")
    sources = (inc.get("evidence") or {}).get("sources") or []
    lines.append(f"evidence sources: {', '.join(sources) or '(none)'}")
    return "\n".join(lines)


def _render_incidents(body: dict) -> str:
    """All three wire shapes: the router federation (`replicas` +
    `fleet` rollup), a bare replica's report (`incidents`), and the
    `?id=` full record."""
    if body.get("id"):
        return _render_incident_detail(body)
    lines = []
    if "fleet" in body or "replicas" in body:
        replicas = sorted((body.get("replicas") or {}).keys())
        lines.append(f"replicas: {', '.join(replicas) or '(none)'}")
        fleet = body.get("fleet") or []
        lines.append(f"fleet incidents: {len(fleet)} "
                     f"({body.get('open', 0)} open)")
        for f in fleet:
            state = "OPEN" if f.get("open") else "closed"
            lines.append(
                f"[{state}] {f.get('root_cause') or 'unclassified'} "
                f"model={f.get('model')} x{f.get('count')} on "
                f"{len(f.get('replicas') or [])} replica(s)")
            if f.get("top_hypothesis"):
                lines.append("  " + _fmt_hyp(f["top_hypothesis"]))
            for ref in (f.get("incident_ids") or [])[:5]:
                lines.append(f"  {ref.get('replica')}: "
                             f"{ref.get('id')}")
        brown = ((body.get("router") or {})
                 .get("brownout_levels")) or {}
        active = {m: lvl for m, lvl in brown.items() if lvl}
        if active:
            lines.append("router brownout: " + ", ".join(
                f"{m}=L{lvl}" for m, lvl in sorted(active.items())))
    else:
        lines.append("replicas: (single replica)")
        if body.get("enabled") is False:
            lines.append("incident engine disabled (KFS_INCIDENTS=0)")
            return "\n".join(lines)
        incidents = body.get("incidents") or []
        lines.append(f"incidents: {len(incidents)} "
                     f"({body.get('open', 0)} open, "
                     f"{body.get('total_opened', 0)} opened total)")
        for inc in incidents:
            state = ("OPEN" if inc.get("state") == "open"
                     else inc.get("state"))
            lines.append(
                f"[{state}] {inc.get('id')} "
                f"{inc.get('root_cause') or 'unclassified'} "
                f"model={inc.get('model')}")
            if inc.get("top_hypothesis"):
                lines.append("  " + _fmt_hyp(inc["top_hypothesis"]))
    return "\n".join(lines)


# Series the doctor digests alongside the incident list: tail
# latency, the trend detector's slopes, and the MFU / KV-pool
# occupancy snapshot.
_DOCTOR_SERIES = (
    "kfserving_tpu_request_latency_ms_p99",
    "kfserving_tpu_trend_slope_per_second",
    "kfserving_tpu_engine_mfu",
    "kfserving_tpu_generator_pool_occupancy_ratio",
)


def _series_list(body: dict) -> list:
    """History series in either wire shape (router fleet rollup vs a
    bare replica's flat list)."""
    if "series" in body and "fleet" not in body:
        return body.get("series") or []
    return body.get("fleet") or []


def _render_doctor(incidents_body: dict, histories: dict) -> str:
    open_count = incidents_body.get("open", 0) or 0
    verdict = ("HEALTHY — no open incidents" if not open_count
               else f"ATTENTION — {open_count} open incident(s)")
    lines = [f"kfs doctor: {verdict}", "", "-- incidents --",
             _render_incidents(incidents_body), "", "-- signals --"]
    for name, body in histories.items():
        if body.get("_error"):
            lines.append(f"  {name}: unavailable ({body['_error']})")
            continue
        series = _series_list(body)
        if not series:
            lines.append(f"  {name}: (no frames)")
            continue
        for s in series[:8]:
            values = [f[1] for f in (s.get("frames") or [])]
            if not values:
                continue
            label = ",".join(f"{k}={v}" for k, v in
                             sorted((s.get("labels") or {}).items()))
            head = s.get("name", name) + (f"{{{label}}}"
                                          if label else "")
            lines.append(f"  {head}: last={values[-1]:.4g} "
                         f"min={min(values):.4g} "
                         f"max={max(values):.4g}  "
                         + _sparkline(values[-40:]))
    return "\n".join(lines)


def _read_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _write_json(path: str, data: dict) -> None:
    with open(path, "w") as f:
        json.dump(data, f)


async def _run(args) -> dict:
    loop = asyncio.get_running_loop()
    async with KFServingClient(args.control_url, args.ingress_url) as c:
        ns = args.namespace
        if args.command == "apply":
            spec = await loop.run_in_executor(None, _read_json,
                                              args.filename)
            return await c.create(spec)
        if args.command == "get":
            return await c.get(args.name, ns) if args.name \
                else await c.get()
        if args.command == "delete":
            return await c.delete(args.name, ns)
        if args.command == "wait":
            await c.wait_isvc_ready(args.name, ns,
                                    timeout_seconds=args.timeout)
            return {"name": args.name, "ready": True}
        if args.command == "predict":
            return await c.predict(args.name,
                                   await _payload_async(args),
                                   protocol=args.protocol,
                                   model_name=args.model)
        if args.command == "explain":
            return await c.explain(args.name,
                                   await _payload_async(args))
        if args.command == "canary":
            return await c.rollout_canary(args.name, args.percent, ns)
        if args.command == "promote":
            return await c.promote(args.name, ns)
        if args.command == "rollouts":
            return await c.rollouts()
        if args.command == "cache":
            return await c.cache(replica=args.replica,
                                 top_k=args.top_k,
                                 top_cost=args.top_cost)
        if args.command == "incidents":
            body = await c.incidents(incident_id=args.id,
                                     state=args.state,
                                     limit=args.limit,
                                     replica=args.replica)
            if args.json:
                return body
            return {"_rendered": _render_incidents(body)}
        if args.command == "doctor":
            incidents_body = await c.incidents()
            histories = {}
            for name in _DOCTOR_SERIES:
                try:
                    histories[name] = await c.history(series=name)
                except Exception as e:
                    # A partial digest still diagnoses: a replica
                    # without the history ring just loses sparklines.
                    histories[name] = {"_error": str(e)}
            out = {"_rendered": _render_doctor(incidents_body,
                                               histories)}
            if getattr(args, "exit_code", False) and \
                    (incidents_body.get("open", 0) or 0):
                # Health-gate mode: open incidents flip the process
                # exit status so cron/CI wrappers need no parsing.
                out["_exit_code"] = 2
            return out
        if args.command == "history":
            labels = None
            if args.labels:
                labels = {}
                for pair in args.labels.split(","):
                    if "=" not in pair:
                        raise SystemExit(
                            "--labels must be k=v[,k2=v2...]")
                    k, v = pair.split("=", 1)
                    labels[k] = v
            body = await c.history(series=args.series, labels=labels,
                                   window_s=args.window,
                                   step_s=args.step,
                                   replica=args.replica)
            if args.json:
                return body
            # Rendered (not JSON) output: main() prints this text
            # verbatim so the sparkline glyphs survive.
            return {"_rendered": _render_history(body)}
        if args.command == "profile":
            trace = await c.profile(window_s=args.window,
                                    replica=args.replica)
            await loop.run_in_executor(None, _write_json,
                                       args.output, trace)
            return {"saved": args.output,
                    "events": len(trace.get("traceEvents", []))}
        if args.command == "credentials":
            if args.creds_command == "set-gcs":
                name = await c.set_gcs_credentials(
                    args.credentials_file, args.service_account)
                return {"secret": name,
                        "serviceAccount": args.service_account}
            if args.creds_command == "set-s3":
                name = await c.set_s3_credentials(
                    args.credentials_file, args.service_account,
                    s3_profile=args.profile, s3_endpoint=args.endpoint,
                    s3_region=args.region, s3_use_https=args.use_https,
                    s3_verify_ssl=args.verify_ssl)
                return {"secret": name,
                        "serviceAccount": args.service_account}
            if args.creds_command == "set-azure":
                name = await c.set_azure_credentials(
                    args.credentials_file, args.service_account)
                return {"secret": name,
                        "serviceAccount": args.service_account}
            if args.creds_command == "list":
                return await c.list_secrets()
            if args.creds_command == "delete":
                return await c.delete_secret(args.name)
        if args.command == "trainedmodel":
            if args.tm_command == "apply":
                return await c.create_trained_model(
                    await loop.run_in_executor(None, _read_json,
                                               args.filename))
            if args.tm_command == "delete":
                return await c.delete_trained_model(args.name, ns)
            return await c.get_trained_model(args.name, ns) \
                if args.name else await c.get_trained_model()
        raise SystemExit(f"unknown command {args.command}")


def main(argv=None) -> int:
    args = parser.parse_args(argv)
    try:
        result = asyncio.run(_run(args))
    except Exception as e:
        print(json.dumps({"error": str(e)}), file=sys.stderr)
        return 1
    if isinstance(result, dict) and "_rendered" in result:
        print(result["_rendered"])
        return int(result.get("_exit_code", 0))
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
