"""SDK client + CLI for the TPU serving fabric (reference
python/kfserving/kfserving/api/kf_serving_client.py equivalent)."""

from kfserving_tpu.client.client import (
    ClientError,
    KFServingClient,
    isvc_spec,
)

__all__ = ["KFServingClient", "ClientError", "isvc_spec"]
