import sys

from kfserving_tpu.client.cli import main

sys.exit(main())
